"""Durable time-series store over the controller's federated scrapes.

The serve controller already scrapes its LB's federated ``/metrics``
once per decision tick (every ready replica's engine series relabeled
``replica="<id>"``).  This module downsamples those scrapes into a
retention-bounded table behind the pluggable state backend, so trend
queries (burn rates, sparklines, `skytpu top`) survive process
restarts and are visible from any control-plane replica:

- **histograms** (TTFT/TPOT/LB duration): cumulative-since-boot per
  series; the Downsampler computes per-series bucket DELTAS with the
  same counter-reset clamping as ``metrics_math.WindowedHistogram`` —
  a restarted replica re-baselines instead of going negative, a
  rejoining series contributes nothing until its second scrape — and
  the store keeps the deltas summed per pool (events per interval);
- **counters**: per-series reset-clamped deltas, summed per pool (an
  optional sub-label, e.g. ``outcome``, lands in the ``bucket`` key);
- **gauges**: point-in-time values kept per replica (free pages,
  spec acceptance, prefix fingerprint, scrape age).

Row key: ``(service, pool, replica, family, bucket, t)`` where ``t``
is the resolution-aligned interval start.  Knobs:
``SKYTPU_OBS_RESOLUTION_S`` (interval width, default 10 s) and
``SKYTPU_OBS_RETENTION_S`` (default 21600 s = 6 h — the slow burn
window below needs it).  Ingest is WRITTEN ONLY BY THE SINGLETON-LEASE
HOLDER when lease mode is on (multi-replica control planes must not
double-count deltas); every ingest also writes one
``skytpu_obs_ingest_total`` heartbeat row, which is what the
dark-scrape alert rule measures gaps in.

All SQL goes through utils/db_utils (skytpu check: db-discipline), so
the table exists identically on sqlite and Postgres via the PR 15
dialect layer.
"""
from __future__ import annotations

import math
import os
import time
from typing import Dict, List, Optional, Tuple

from skypilot_tpu.serve import metrics_math
from skypilot_tpu.server import metrics as metrics_lib
from skypilot_tpu.state import leases
from skypilot_tpu.utils import db_utils

RESOLUTION_ENV = 'SKYTPU_OBS_RESOLUTION_S'
RETENTION_ENV = 'SKYTPU_OBS_RETENTION_S'
DEFAULT_RESOLUTION_S = 10.0
DEFAULT_RETENTION_S = 21600.0
# Singleton-lease role gating ingest in lease mode (one writer per
# fleet — two control-plane replicas double-COUNTING deltas would halve
# every rate's apparent interval).
INGEST_LEASE = 'obs-ingest'
# Per-ingest heartbeat family: one row per performed ingest interval.
# Registered in server/metrics.py _HELP (the registry counter twin is
# incremented on every ingest), so alert rules may reference it.
INGEST_FAMILY = 'skytpu_obs_ingest_total'

# What gets downsampled out of a federated scrape.  Histograms keep
# their per-bucket deltas (quantiles need the distribution); counters
# keep event deltas; gauges keep per-replica point-in-time values.
HISTOGRAM_FAMILIES: Tuple[str, ...] = (
    metrics_lib.ENGINE_TTFT_FAMILY,
    metrics_lib.ENGINE_TPOT_FAMILY,
    'skytpu_lb_request_duration_seconds',
    metrics_lib.TRAIN_STEP_FAMILY,
)
# histogram family -> sub-label kept through downsampling (lands in
# the `replica` column, one distribution per label value).  Step-time
# histograms keep their `host` label so straggler skew (max-host p50 /
# median-host p50, obs/goodput.py) is derivable from store rows alone.
HISTOGRAM_SUB_FAMILIES: Dict[str, str] = {
    metrics_lib.TRAIN_STEP_FAMILY: 'host',
}
# family -> sub-label whose value keys the `bucket` column (None:
# aggregate every series of the family into one row per interval).
COUNTER_FAMILIES: Dict[str, Optional[str]] = {
    'skytpu_lb_requests_total': None,
    'skytpu_lb_shed_total': None,
    'skytpu_engine_requests_total': None,
    'skytpu_engine_prefix_cache_hits_total': None,
    'skytpu_engine_prefix_cache_misses_total': None,
    'skytpu_engine_spec_proposed_tokens_total': None,
    'skytpu_engine_spec_accepted_tokens_total': None,
    'skytpu_fleetsim_requests_total': 'outcome',
}
GAUGE_FAMILIES: Tuple[str, ...] = (
    'skytpu_engine_kv_free_pages',
    'skytpu_engine_spec_acceptance',
    'skytpu_engine_prefix_fingerprint',
    'skytpu_engine_mfu',
    metrics_lib.QUEUED_PREFILL_TOKENS_FAMILY,
    'skytpu_lb_scrape_age_seconds',
    # Training goodput plane: the headline goodput gauge from worker
    # scrapes; the skew gauge is DERIVED controller-side
    # (obs/goodput.evaluate_stragglers writes it via put_gauge) but
    # listed so a federated re-scrape of a controller round-trips it.
    metrics_lib.TRAIN_GOODPUT_FAMILY,
    metrics_lib.TRAIN_STEP_SKEW_FAMILY,
)

_DDL = [
    """CREATE TABLE IF NOT EXISTS obs_samples (
        service TEXT NOT NULL,
        pool TEXT NOT NULL,
        replica TEXT NOT NULL,
        family TEXT NOT NULL,
        bucket TEXT NOT NULL,
        t REAL NOT NULL,
        value REAL NOT NULL,
        PRIMARY KEY (service, pool, replica, family, bucket, t))""",
    """CREATE INDEX IF NOT EXISTS obs_samples_family_t
        ON obs_samples (service, family, t)""",
    """CREATE TABLE IF NOT EXISTS obs_alerts (
        service TEXT NOT NULL,
        rule TEXT NOT NULL,
        pool TEXT NOT NULL,
        state TEXT NOT NULL,
        fired_at REAL NOT NULL,
        cleared_at REAL,
        burn REAL,
        detail TEXT,
        PRIMARY KEY (service, rule, fired_at))""",
]


def resolution_s() -> float:
    try:
        return float(os.environ.get(RESOLUTION_ENV,
                                    DEFAULT_RESOLUTION_S))
    except ValueError:
        return DEFAULT_RESOLUTION_S


def retention_s() -> float:
    try:
        return float(os.environ.get(RETENTION_ENV, DEFAULT_RETENTION_S))
    except ValueError:
        return DEFAULT_RETENTION_S


def _le_text(le: float) -> str:
    """Stable text key for a histogram bound (the `bucket` column)."""
    return '+Inf' if math.isinf(le) else repr(float(le))


def _le_value(text: str) -> float:
    return math.inf if text == '+Inf' else float(text)


class Downsampler:
    """Per-series reset-aware delta extraction from successive scrapes.

    Holds one baseline per (family, series-label-set).  A scrape's
    delta for a series is ``current - baseline`` clamped at zero; a
    series whose cumulative values went BACKWARD (replica restart
    zeroes its registry) or that was never seen before contributes
    NOTHING this scrape and only re-baselines — the same one-window-of-
    partial-vision-beats-negative-deltas posture as
    metrics_math.WindowedHistogram.  Baselines unseen for
    ``forget_after_s`` are dropped, so a replica that churns out and
    back after a long absence is just a new series (its since-boot
    cumulative counts are never mistaken for one interval's events).
    """

    def __init__(self, forget_after_s: float = 600.0) -> None:
        self.forget_after_s = forget_after_s
        # (family, series_key) -> {le: cumulative} | float
        self._hist: Dict[Tuple[str, tuple], Dict[float, float]] = {}
        self._counters: Dict[Tuple[str, tuple], float] = {}
        self._seen: Dict[Tuple[str, tuple], float] = {}

    @staticmethod
    def _series_key(labels: Dict[str, str]) -> tuple:
        return tuple(sorted((k, v) for k, v in labels.items()
                            if k != 'le'))

    def _touch(self, key: Tuple[str, tuple], now: float) -> None:
        self._seen[key] = now

    def _forget_stale(self, now: float) -> None:
        stale = [k for k, seen in self._seen.items()
                 if now - seen > self.forget_after_s]
        for k in stale:
            del self._seen[k]
            self._hist.pop(k, None)
            self._counters.pop(k, None)

    def observe(self, samples: List[Tuple[str, Dict[str, str], float]],
                now: float, roles: Optional[Dict[str, str]] = None
                ) -> Dict[str, Dict[tuple, float]]:
        """One scrape in, pool-aggregated deltas/gauges out.

        Returns ``{'hist': {(family, pool, sub, le_text): delta},
        'counters': {(family, pool, bucket): delta},
        'gauges': {(family, pool, replica): value}}``.  ``roles`` maps
        replica label -> pool name for pool attribution; unlabeled or
        unknown series land under pool ''.  ``sub`` is the series'
        HISTOGRAM_SUB_FAMILIES label value ('' for families without a
        sub-label); it lands in the store's ``replica`` column so
        per-host step-time distributions survive downsampling.
        """
        roles = roles or {}
        hist: Dict[tuple, float] = {}
        counters: Dict[tuple, float] = {}
        gauges: Dict[tuple, float] = {}

        for family in HISTOGRAM_FAMILIES:
            by_series = metrics_math.histogram_cumulative_by_series(
                samples, family)
            for skey, cum in by_series.items():
                key = (family, skey)
                prev = self._hist.get(key)
                self._hist[key] = dict(cum)
                self._touch(key, now)
                if prev is None or any(
                        cum.get(le, 0.0) < count - 1e-9
                        for le, count in prev.items()):
                    continue  # new series or reset: baseline only
                pool = self._pool_of(skey, roles)
                sub_label = HISTOGRAM_SUB_FAMILIES.get(family)
                sub = (dict(skey).get(sub_label, '')
                       if sub_label else '')
                for le, count in cum.items():
                    delta = count - prev.get(le, 0.0)
                    if delta > 0.0:
                        k = (family, pool, sub, _le_text(le))
                        hist[k] = hist.get(k, 0.0) + delta

        for name, labels, value in samples:
            if name in COUNTER_FAMILIES and math.isfinite(value):
                skey = self._series_key(labels)
                key = (name, skey)
                prev = self._counters.get(key)
                self._counters[key] = value
                self._touch(key, now)
                if prev is None or value < prev - 1e-9:
                    continue  # new series or reset: baseline only
                delta = value - prev
                if delta <= 0.0:
                    continue
                pool = roles.get(labels.get('replica', ''), '')
                sub_label = COUNTER_FAMILIES[name]
                bucket = labels.get(sub_label, '') if sub_label else ''
                k = (name, pool, bucket)
                counters[k] = counters.get(k, 0.0) + delta
            elif name in GAUGE_FAMILIES and math.isfinite(value):
                replica = labels.get('replica', '')
                pool = roles.get(replica, '')
                gauges[(name, pool, replica)] = value

        self._forget_stale(now)
        return {'hist': hist, 'counters': counters, 'gauges': gauges}

    @staticmethod
    def _pool_of(series_key: tuple, roles: Dict[str, str]) -> str:
        labels = dict(series_key)
        return roles.get(labels.get('replica', ''), '')


class TelemetryStore:
    """The durable fleet time-series table + its query API.

    One instance per (dsn, service-scope); safe to construct cheaply —
    schema creation is memoized by db_utils.ensure_schema.
    """

    def __init__(self, dsn: str,
                 resolution: Optional[float] = None,
                 retention: Optional[float] = None) -> None:
        self.dsn = dsn
        self.resolution = (resolution_s() if resolution is None
                           else float(resolution))
        self.retention = (retention_s() if retention is None
                          else float(retention))
        self._down = Downsampler(
            forget_after_s=max(60.0, 10.0 * self.resolution))
        self._last_prune_bucket: Optional[float] = None

    def _ensure(self) -> str:
        db_utils.ensure_schema(self.dsn, _DDL)
        return self.dsn

    def bucket_t(self, now: float) -> float:
        res = max(self.resolution, 1e-9)
        return math.floor(now / res) * res

    # ----- ingest -------------------------------------------------------------
    def ingest(self, service: str, text: str,
               now: Optional[float] = None,
               roles: Optional[Dict[str, str]] = None,
               leader_check: bool = True) -> bool:
        """Downsample one federated scrape into the table.

        Returns False (writing NOTHING) when lease mode is on and this
        process does not hold the obs-ingest singleton lease — the
        second control-plane replica of an HA deployment must observe,
        not write.  Callers that already gated the tick on a singleton
        lease (the fleetsim decision tick) pass ``leader_check=False``
        rather than re-acquiring per scrape.
        """
        now = time.time() if now is None else now
        if self.resolution <= 0:
            return False
        if leader_check and leases.lease_mode(self.dsn):
            if not leases.try_acquire_singleton(self.dsn, INGEST_LEASE):
                return False
        t0 = time.perf_counter()
        dsn = self._ensure()
        deltas = self._down.observe(metrics_math.parse_samples(text),
                                    now, roles)
        tb = self.bucket_t(now)
        add_sql = (
            'INSERT INTO obs_samples '
            '(service, pool, replica, family, bucket, t, value) '
            'VALUES (?,?,?,?,?,?,?) '
            'ON CONFLICT(service, pool, replica, family, bucket, t) '
            'DO UPDATE SET value = obs_samples.value + excluded.value')
        set_sql = (
            'INSERT INTO obs_samples '
            '(service, pool, replica, family, bucket, t, value) '
            'VALUES (?,?,?,?,?,?,?) '
            'ON CONFLICT(service, pool, replica, family, bucket, t) '
            'DO UPDATE SET value = excluded.value')
        with db_utils.transaction(dsn) as conn:
            for (family, pool, sub, bucket), delta in \
                    deltas['hist'].items():
                conn.execute(add_sql, (service, pool, sub, family,
                                       bucket, tb, delta))
            for (family, pool, bucket), delta in \
                    deltas['counters'].items():
                conn.execute(add_sql, (service, pool, '', family,
                                       bucket, tb, delta))
            for (family, pool, replica), value in \
                    deltas['gauges'].items():
                conn.execute(set_sql, (service, pool, replica, family,
                                       '', tb, value))
            # Ingest heartbeat: the dark-scrape rule measures gaps in
            # THIS family's interval coverage.
            conn.execute(add_sql, (service, '', '', INGEST_FAMILY, '',
                                   tb, 1.0))
        self._prune(service, now)
        metrics_lib.inc_counter(INGEST_FAMILY, service=service)
        metrics_lib.observe_hist('skytpu_obs_ingest_seconds',
                                 time.perf_counter() - t0,
                                 service=service)
        return True

    def _prune(self, service: str, now: float) -> None:
        """Retention: drop rows older than the horizon, at most once
        per resolution interval (a DELETE per scrape would double the
        write load for nothing)."""
        tb = self.bucket_t(now)
        if self._last_prune_bucket == tb:
            return
        self._last_prune_bucket = tb
        db_utils.execute(
            self._ensure(),
            'DELETE FROM obs_samples WHERE service=? AND t < ?',
            (service, now - self.retention))

    # ----- query API ----------------------------------------------------------
    def histogram_window(self, service: str, family: str,
                         t0: float, t1: float,
                         pool: Optional[str] = None
                         ) -> Dict[float, float]:
        """Summed per-bucket event counts in ``(t0, t1]`` as a
        cumulative-shaped {le: count} map (feedable to
        metrics_math.quantile_from_cumulative)."""
        sql = ('SELECT bucket, value FROM obs_samples WHERE service=? '
               'AND family=? AND t > ? AND t <= ?')
        params: list = [service, family, t0, t1]
        if pool is not None:
            sql += ' AND pool=?'
            params.append(pool)
        agg: Dict[float, float] = {}
        for row in db_utils.query(self._ensure(), sql, tuple(params)):
            try:
                le = _le_value(row['bucket'])
            except ValueError:
                continue
            agg[le] = agg.get(le, 0.0) + float(row['value'])
        return agg

    def histogram_window_by_replica(self, service: str, family: str,
                                    t0: float, t1: float
                                    ) -> Dict[str, Dict[float, float]]:
        """Per-replica-column bucket counts in ``(t0, t1]`` — for
        sub-labeled histogram families (HISTOGRAM_SUB_FAMILIES) the
        replica column holds the sub-label value (e.g. ``host``), so
        this is the per-host step-time distribution the straggler
        detector compares quantiles across."""
        sql = ('SELECT replica, bucket, value FROM obs_samples WHERE '
               'service=? AND family=? AND t > ? AND t <= ?')
        out: Dict[str, Dict[float, float]] = {}
        for row in db_utils.query(self._ensure(), sql,
                                  (service, family, t0, t1)):
            try:
                le = _le_value(row['bucket'])
            except ValueError:
                continue
            agg = out.setdefault(row['replica'], {})
            agg[le] = agg.get(le, 0.0) + float(row['value'])
        return out

    def quantile(self, service: str, family: str, t0: float, t1: float,
                 q: float, pool: Optional[str] = None
                 ) -> Optional[float]:
        return metrics_math.quantile_from_cumulative(
            self.histogram_window(service, family, t0, t1, pool), q)

    def counter_sum(self, service: str, family: str,
                    t0: float, t1: float,
                    bucket: Optional[str] = None,
                    pool: Optional[str] = None) -> float:
        sql = ('SELECT COALESCE(SUM(value), 0) AS s FROM obs_samples '
               'WHERE service=? AND family=? AND t > ? AND t <= ?')
        params: list = [service, family, t0, t1]
        if bucket is not None:
            sql += ' AND bucket=?'
            params.append(bucket)
        if pool is not None:
            sql += ' AND pool=?'
            params.append(pool)
        row = db_utils.query_one(self._ensure(), sql, tuple(params))
        return float(row['s']) if row is not None else 0.0

    def gauge_min(self, service: str, family: str, t0: float, t1: float,
                  pool: Optional[str] = None) -> Optional[float]:
        """Worst (lowest) gauge value any replica reported in the
        window — the exhaustion signal for floor-type rules."""
        sql = ('SELECT MIN(value) AS m FROM obs_samples WHERE '
               'service=? AND family=? AND t > ? AND t <= ?')
        params: list = [service, family, t0, t1]
        if pool is not None:
            sql += ' AND pool=?'
            params.append(pool)
        row = db_utils.query_one(self._ensure(), sql, tuple(params))
        if row is None or row['m'] is None:
            return None
        return float(row['m'])

    def gauge_max(self, service: str, family: str, t0: float, t1: float,
                  pool: Optional[str] = None) -> Optional[float]:
        """Worst (highest) gauge value in the window — the ceiling
        signal for gauge_high rules (step-time skew)."""
        sql = ('SELECT MAX(value) AS m FROM obs_samples WHERE '
               'service=? AND family=? AND t > ? AND t <= ?')
        params: list = [service, family, t0, t1]
        if pool is not None:
            sql += ' AND pool=?'
            params.append(pool)
        row = db_utils.query_one(self._ensure(), sql, tuple(params))
        if row is None or row['m'] is None:
            return None
        return float(row['m'])

    def put_gauge(self, service: str, family: str, value: float,
                  now: float, pool: str = '', replica: str = '') -> None:
        """Write one DERIVED gauge interval directly (not via a
        scrape) — how the controller lands computed signals like
        step-time skew in the same table its alert rules read."""
        db_utils.execute(
            self._ensure(),
            'INSERT INTO obs_samples '
            '(service, pool, replica, family, bucket, t, value) '
            'VALUES (?,?,?,?,?,?,?) '
            'ON CONFLICT(service, pool, replica, family, bucket, t) '
            'DO UPDATE SET value = excluded.value',
            (service, pool, replica, family, '', self.bucket_t(now),
             float(value)))

    def gauge_latest(self, service: str, family: str,
                     replica: Optional[str] = None,
                     pool: Optional[str] = None
                     ) -> Dict[str, float]:
        """Latest value per replica label (newest interval wins)."""
        sql = ('SELECT replica, t, value FROM obs_samples WHERE '
               'service=? AND family=?')
        params: list = [service, family]
        if replica is not None:
            sql += ' AND replica=?'
            params.append(replica)
        if pool is not None:
            sql += ' AND pool=?'
            params.append(pool)
        sql += ' ORDER BY t'
        out: Dict[str, float] = {}
        for row in db_utils.query(self._ensure(), sql, tuple(params)):
            out[row['replica']] = float(row['value'])
        return out

    def series(self, service: str, family: str, t0: float, t1: float,
               bucket: Optional[str] = None,
               pool: Optional[str] = None
               ) -> List[Tuple[float, float]]:
        """(t, summed value) per interval — sparkline feedstock."""
        sql = ('SELECT t, SUM(value) AS v FROM obs_samples WHERE '
               'service=? AND family=? AND t > ? AND t <= ?')
        params: list = [service, family, t0, t1]
        if bucket is not None:
            sql += ' AND bucket=?'
            params.append(bucket)
        if pool is not None:
            sql += ' AND pool=?'
            params.append(pool)
        sql += ' GROUP BY t ORDER BY t'
        return [(float(r['t']), float(r['v']))
                for r in db_utils.query(self._ensure(), sql,
                                        tuple(params))]

    def first_t(self, service: str, family: str) -> Optional[float]:
        """Oldest retained interval of a family — the dark-scrape rule
        only counts an interval as missing once the store has history
        reaching back to it (a fresh deployment is not dark)."""
        row = db_utils.query_one(
            self._ensure(),
            'SELECT MIN(t) AS m FROM obs_samples WHERE service=? '
            'AND family=?', (service, family))
        if row is None or row['m'] is None:
            return None
        return float(row['m'])

    def last_t(self, service: str) -> Optional[float]:
        """Newest retained interval of the service — `skytpu top`'s
        frame anchor, so a postmortem view of a dead fleet (or a
        sim-time store) lands on the data instead of an empty
        wall-clock window."""
        row = db_utils.query_one(
            self._ensure(),
            'SELECT MAX(t) AS m FROM obs_samples WHERE service=?',
            (service,))
        if row is None or row['m'] is None:
            return None
        return float(row['m'])

    def present_intervals(self, service: str, family: str,
                          t0: float, t1: float) -> int:
        """Distinct resolution intervals holding any row of the family
        in ``(t0, t1]`` — the dark-scrape rule's coverage count."""
        row = db_utils.query_one(
            self._ensure(),
            'SELECT COUNT(DISTINCT t) AS n FROM obs_samples WHERE '
            'service=? AND family=? AND t > ? AND t <= ?',
            (service, family, t0, t1))
        return int(row['n']) if row is not None else 0

    def services(self) -> List[str]:
        return [r['service'] for r in db_utils.query(
            self._ensure(),
            'SELECT DISTINCT service FROM obs_samples ORDER BY service')]

    def pools(self, service: str, t0: float, t1: float) -> List[str]:
        """Distinct pool tags with any row in ``(t0, t1]`` ('' =
        unattributed, e.g. LB-level families)."""
        return [r['pool'] for r in db_utils.query(
            self._ensure(),
            'SELECT DISTINCT pool FROM obs_samples WHERE service=? '
            'AND t > ? AND t <= ? ORDER BY pool',
            (service, t0, t1))]

    # ----- alert rows (written by obs/alerts.py, read by CLI/LB) --------------
    def fire_alert(self, service: str, rule: str, pool: str,
                   fired_at: float, burn: float, detail: str) -> None:
        db_utils.execute(
            self._ensure(),
            'INSERT INTO obs_alerts '
            '(service, rule, pool, state, fired_at, burn, detail) '
            "VALUES (?,?,?,'firing',?,?,?)",
            (service, rule, pool, fired_at, burn, detail))

    def clear_alert(self, service: str, rule: str,
                    cleared_at: float) -> None:
        db_utils.execute(
            self._ensure(),
            "UPDATE obs_alerts SET state='cleared', cleared_at=? "
            "WHERE service=? AND rule=? AND state='firing'",
            (cleared_at, service, rule))

    def active_alerts(self, service: Optional[str] = None
                      ) -> List[Dict]:
        sql = ("SELECT * FROM obs_alerts WHERE state='firing'")
        params: tuple = ()
        if service is not None:
            sql += ' AND service=?'
            params = (service,)
        sql += ' ORDER BY fired_at'
        return [dict(r) for r in db_utils.query(self._ensure(), sql,
                                                params)]

    def alert_history(self, service: Optional[str] = None,
                      limit: int = 100) -> List[Dict]:
        sql = 'SELECT * FROM obs_alerts'
        params: tuple = ()
        if service is not None:
            sql += ' WHERE service=?'
            params = (service,)
        sql += ' ORDER BY fired_at DESC LIMIT ?'
        return [dict(r) for r in db_utils.query(
            self._ensure(), sql, params + (int(limit),))]
