"""Multi-window SLO burn-rate alerting over the telemetry store.

Rules are DECLARATIVE: a frozen `AlertRule` names a registered metric
family (skytpu check's metric-naming rule statically verifies the
reference), a burn semantic (`kind`), and hysteresis ratios.  The
engine turns each rule into a dimensionless **burn rate** — "how many
multiples of the SLO budget is this signal consuming right now" — and
applies the classic multi-window discipline (Google SRE workbook ch.5):
an alert fires only when the burn exceeds the threshold on BOTH windows
of a pair (the long window proves it is sustained, the short window
makes the alert responsive and lets it clear quickly), with a fast pair
(5 m / 1 h) for page-worthy burns and a slow pair (30 m / 6 h) for
budget-eroding simmer.  Transitions are durable `obs_alerts` rows plus
`alert.fire`/`alert.clear` instants in the flight recorder, so a storm's
alert timeline is auditable after the fact (`skytpu trace`,
`skytpu alerts --history`).

Burn semantics per kind (burn >= 1.0 means "out of SLO"):

- ``latency_burn``: windowed p95 of a latency histogram vs a
  millisecond target — ``p95_s * 1000 / target_ms``;
- ``ratio``: two counter families (e.g. shed / total requests) vs a
  target fraction — ``(num / den) / target``;
- ``gauge_low``: a floor on the worst per-replica gauge in the window
  (free pages, spec acceptance) — ``target / min_value``;
- ``gauge_high``: the symmetric ceiling — ``max_value / target`` —
  for signals where HIGH is bad (step-time skew: a straggling host
  drags every synchronous step to its pace);
- ``missing``: fraction of resolution intervals with NO ingest
  heartbeat vs a target fraction — the dark-scrape signal.  Evaluated
  on the fast short window only (absence is inherently a now-signal,
  not an error budget) and guarded by the store's oldest heartbeat so
  a fresh deployment is not instantly "dark".

The fleetsim chaos run drives this exact engine with second-scale
windows, which is how the canonical storm's fire/clear ticks get
test-pinned.
"""
from __future__ import annotations

import dataclasses
import json
import math
from typing import Dict, List, Optional, Sequence, Tuple

from skypilot_tpu.obs import store as store_lib
from skypilot_tpu.server import metrics as metrics_lib
from skypilot_tpu.server import tracing

# Synthetic request id for flight-recorder instants (same idiom as the
# recompile sentinel): alert transitions are fleet events, not request
# events, but they belong on the same timeline.
ALERT_RID = 'alert-engine'
ALERTS_FAMILY = 'skytpu_obs_alerts_total'

# Module constant so the dark-scrape rule's family reference below is
# statically resolvable by skytpu check's metric-naming rule.
DARK_SCRAPE_FAMILY = 'skytpu_obs_ingest_total'


@dataclasses.dataclass(frozen=True)
class BurnWindows:
    """(short, long) seconds per pair.  Production defaults follow the
    SRE-workbook pairs; fleetsim scales them to sim seconds."""
    fast: Tuple[float, float] = (300.0, 3600.0)
    slow: Tuple[float, float] = (1800.0, 21600.0)


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One declarative SLO rule.

    ``family`` (and ``ratio_family`` for kind='ratio') MUST name a
    registered metric family — skytpu check resolves these keyword
    arguments statically against server/metrics._HELP.  ``pool`` is
    attribution metadata carried onto fired alerts (which pool the
    operator should look at), not a query filter: store rows are
    pool-tagged only when the scrape carries replica labels.
    """
    name: str
    kind: str  # latency_burn|ratio|gauge_low|gauge_high|missing
    family: str
    pool: str = ''
    target: float = 1.0
    ratio_family: str = ''
    bucket: str = ''  # counter sub-label filter ('' = all)
    fire_ratio: float = 1.0
    clear_ratio: float = 0.9

    def __post_init__(self) -> None:
        if self.kind not in ('latency_burn', 'ratio', 'gauge_low',
                             'gauge_high', 'missing'):
            raise ValueError(f'unknown alert rule kind: {self.kind!r}')
        if self.kind == 'ratio' and not self.ratio_family:
            raise ValueError(
                f'rule {self.name!r}: kind=ratio needs ratio_family')


def default_rules(target_ttft_ms: float, target_tpot_ms: float,
                  dark_scrape_target: float = 0.4
                  ) -> Tuple[AlertRule, ...]:
    """The stock fleet rule set, parameterized by the service spec's
    latency targets (serve_llama.yaml documents each)."""
    return (
        AlertRule(name='ttft_slo_burn', kind='latency_burn',
                  family=metrics_lib.ENGINE_TTFT_FAMILY,
                  pool='prefill', target=float(target_ttft_ms)),
        AlertRule(name='tpot_slo_burn', kind='latency_burn',
                  family=metrics_lib.ENGINE_TPOT_FAMILY,
                  pool='decode', target=float(target_tpot_ms)),
        AlertRule(name='shed_rate', kind='ratio',
                  family='skytpu_lb_shed_total',
                  ratio_family='skytpu_lb_requests_total',
                  target=0.05),
        AlertRule(name='dark_scrape', kind='missing',
                  family=DARK_SCRAPE_FAMILY,
                  target=float(dark_scrape_target)),
        AlertRule(name='spec_acceptance_collapse', kind='gauge_low',
                  family='skytpu_engine_spec_acceptance',
                  pool='decode', target=0.1),
        AlertRule(name='kv_free_pages_exhausted', kind='gauge_low',
                  family='skytpu_engine_kv_free_pages',
                  pool='decode', target=8.0),
    )


def train_rules(goodput_target_pct: float = 80.0,
                skew_target: float = 1.3) -> Tuple[AlertRule, ...]:
    """The training-job rule set (ISSUE 20): `goodput_low` fires when
    the job's goodput gauge sags under the target percentage on both
    windows of a pair; `straggler` fires when the per-window host skew
    (max-host p50 / median-host p50, written by
    obs/goodput.evaluate_stragglers) sustains above `skew_target` —
    on a synchronous job the whole pod runs at the slow host's pace,
    so skew IS the badput multiplier."""
    return (
        AlertRule(name='goodput_low', kind='gauge_low',
                  family=metrics_lib.TRAIN_GOODPUT_FAMILY,
                  pool='train', target=float(goodput_target_pct)),
        AlertRule(name='straggler', kind='gauge_high',
                  family=metrics_lib.TRAIN_STEP_SKEW_FAMILY,
                  pool='train', target=float(skew_target)),
    )


class AlertEngine:
    """Evaluates a rule set against one service's store rows.

    Holds only the firing-set cache — all durable state lives in
    ``obs_alerts`` rows, so a restarted control plane resumes with the
    alerts it left firing instead of re-firing them (the cache is
    seeded from the table on first evaluate)."""

    def __init__(self, store: store_lib.TelemetryStore, service: str,
                 rules: Sequence[AlertRule],
                 windows: Optional[BurnWindows] = None) -> None:
        self.store = store
        self.service = service
        self.rules = tuple(rules)
        self.windows = windows or BurnWindows()
        self._firing: Optional[Dict[str, float]] = None  # rule -> t

    def _seed_firing(self) -> Dict[str, float]:
        if self._firing is None:
            self._firing = {
                row['rule']: float(row['fired_at'])
                for row in self.store.active_alerts(self.service)}
        return self._firing

    # ----- burn computation ---------------------------------------------------
    def _burn(self, rule: AlertRule, now: float, window: float
              ) -> Optional[float]:
        """Dimensionless burn of `rule` over ``(now - window, now]``;
        None when the store has no usable data (no transition)."""
        t0, t1 = now - window, now
        s = self.store
        if rule.kind == 'latency_burn':
            q = s.quantile(self.service, rule.family, t0, t1, 0.95)
            if q is None or rule.target <= 0:
                return None
            return (q * 1000.0) / rule.target
        if rule.kind == 'ratio':
            den = s.counter_sum(self.service, rule.ratio_family, t0, t1)
            if den <= 0 or rule.target <= 0:
                return None
            num = s.counter_sum(self.service, rule.family, t0, t1,
                                bucket=rule.bucket or None)
            return (num / den) / rule.target
        if rule.kind == 'gauge_low':
            worst = s.gauge_min(self.service, rule.family, t0, t1)
            if worst is None or rule.target <= 0:
                return None
            if worst <= 0:
                return math.inf
            return rule.target / worst
        if rule.kind == 'gauge_high':
            worst = s.gauge_max(self.service, rule.family, t0, t1)
            if worst is None or rule.target <= 0:
                return None
            return worst / rule.target
        # kind == 'missing': coverage gaps in the family's intervals,
        # counted only over history the store actually reaches back to.
        first = s.first_t(self.service, rule.family)
        if first is None or rule.target <= 0:
            return None
        res = max(self.store.resolution, 1e-9)
        t0 = max(t0, first)
        expected = int(round((t1 - t0) / res))
        if expected <= 0:
            return None
        present = s.present_intervals(self.service, rule.family, t0, t1)
        missing = max(0, expected - present) / expected
        return missing / rule.target

    def _pair_burns(self, rule: AlertRule, now: float,
                    pair: Tuple[float, float]
                    ) -> Tuple[Optional[float], Optional[float]]:
        return (self._burn(rule, now, pair[0]),
                self._burn(rule, now, pair[1]))

    def _tripped(self, rule: AlertRule, now: float, threshold: float
                 ) -> Tuple[bool, bool, Dict[str, float]]:
        """(any pair trips at `threshold`?, any data at all?,
        window->burn detail).  A pair trips when BOTH its windows' burns
        meet the threshold (the multi-window AND); pairs are ORed.  The
        `missing` kind is single-window (absence is a now-signal, not
        an error budget): the fast short window alone decides."""
        detail: Dict[str, float] = {}
        if rule.kind == 'missing':
            b = self._burn(rule, now, self.windows.fast[0])
            if b is None:
                return False, False, detail
            detail[f'{self.windows.fast[0]:g}s'] = round(b, 4)
            return b >= threshold, True, detail
        tripped = False
        any_data = False
        for pair in (self.windows.fast, self.windows.slow):
            b_short, b_long = self._pair_burns(rule, now, pair)
            for w, b in ((pair[0], b_short), (pair[1], b_long)):
                if b is not None:
                    any_data = True
                    if math.isfinite(b):
                        detail[f'{w:g}s'] = round(b, 4)
            if (b_short is not None and b_long is not None
                    and b_short >= threshold and b_long >= threshold):
                tripped = True
        return tripped, any_data, detail

    def _should_fire(self, rule: AlertRule, now: float
                     ) -> Tuple[bool, Optional[float], Dict[str, float]]:
        """(fire?, peak burn across windows, window->burn detail)."""
        fire, _, detail = self._tripped(rule, now, rule.fire_ratio)
        burn = max(detail.values()) if detail else None
        return fire, burn, detail

    def _should_clear(self, rule: AlertRule, now: float) -> bool:
        """Hysteresis symmetric with the fire condition: clear only
        when NO window pair trips at clear_ratio (clear_ratio <
        fire_ratio makes fire⇒¬clear, so the state machine cannot
        flap) — and never on no-data (a dark fleet keeps its latency
        alerts; dark_scrape covers the dark)."""
        tripped, any_data, _ = self._tripped(rule, now,
                                             rule.clear_ratio)
        return any_data and not tripped

    # ----- the state machine --------------------------------------------------
    def evaluate(self, now: float) -> List[Dict]:
        """One evaluation pass; returns this pass's transitions as
        [{'rule', 'pool', 'transition': 'fire'|'clear', 't', 'burn'}].
        """
        firing = self._seed_firing()
        transitions: List[Dict] = []
        for rule in self.rules:
            if rule.name in firing:
                if self._should_clear(rule, now):
                    del firing[rule.name]
                    self.store.clear_alert(self.service, rule.name, now)
                    tracing.record_instant(
                        ALERT_RID, 'alert.clear', service=self.service,
                        rule=rule.name, pool=rule.pool)
                    metrics_lib.inc_counter(
                        ALERTS_FAMILY, rule=rule.name,
                        transition='clear')
                    transitions.append(
                        {'rule': rule.name, 'pool': rule.pool,
                         'transition': 'clear', 't': now, 'burn': None})
                continue
            fire, burn, detail = self._should_fire(rule, now)
            if not fire:
                continue
            firing[rule.name] = now
            burn_val = (round(burn, 4)
                        if burn is not None and math.isfinite(burn)
                        else -1.0)
            self.store.fire_alert(self.service, rule.name, rule.pool,
                                  now, burn_val,
                                  json.dumps(detail, sort_keys=True))
            tracing.record_instant(
                ALERT_RID, 'alert.fire', service=self.service,
                rule=rule.name, pool=rule.pool, burn=burn_val)
            metrics_lib.inc_counter(ALERTS_FAMILY, rule=rule.name,
                                    transition='fire')
            transitions.append(
                {'rule': rule.name, 'pool': rule.pool,
                 'transition': 'fire', 't': now, 'burn': burn_val})
        return transitions
