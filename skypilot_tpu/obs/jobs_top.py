"""`skytpu jobs top <job>`: the per-job goodput view.

The training twin of `skytpu top` — same posture (pure store/ledger
reader, side-effect-free `render()`, loop in `run()`), different
questions: what fraction of this job's wall-clock produced gradients,
where did the rest go, which host is dragging the pod, and what did
each recovery cost.  Every number comes from durable state (the
goodput ledger + the telemetry store), so a DEAD job renders the same
postmortem a live one renders as a dashboard:

    JOB 7 demo-ft (RUNNING)  goodput 87.3%  wall 412s  recoveries 1
    BADPUT  █████████████████████▒▒▒  productive 87.3%
      checkpoint_save        18.2s   4.4%
      preemption_downtime     9.8s   2.4%
      ...
    HOST       p50 STEP  TREND
    host0        102ms   ▃▃▄▃▃▃
    host1        251ms   ▆▇████   <- slow
    skew 2.46 (slow host1)
    RECOVERY TIMELINE:
      t=1700000123 preemption_downtime 9.8s
      t=1700000133 recovery_relaunch 13.1s
    ALERTS: straggler[train] firing since t=1700000200 (burn 1.9)
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

from skypilot_tpu.obs import goodput as goodput_lib
from skypilot_tpu.obs import top as top_lib
from skypilot_tpu.server import metrics as metrics_lib


def service_of(job: str) -> str:
    """Telemetry-store service scope for a managed job's worker
    scrapes — matches the flight-recorder rid convention."""
    return f'job-{job}'


def snapshot(job: str,
             ledger: Optional[goodput_lib.GoodputLedger] = None,
             store=None,
             job_rec: Optional[Dict] = None,
             now: Optional[float] = None,
             window: float = 300.0) -> Dict:
    """One frame's data.  ``store`` (a TelemetryStore over the job's
    step-time telemetry, service ``job-<id>``) is optional: without it
    the frame still renders the ledger breakdown and recovery timeline
    — the minimum postmortem — just no per-host rows or alerts."""
    job = str(job)
    ledger = ledger or goodput_lib.GoodputLedger()
    totals = ledger.totals(job)
    wall = sum(totals.values())
    badput = [
        {'category': cat, 'seconds': totals[cat],
         'pct': 100.0 * totals[cat] / wall if wall > 0 else 0.0}
        for cat in goodput_lib.BADPUT_CATEGORIES if cat in totals]
    badput.sort(key=lambda b: -b['seconds'])
    recoveries = [iv for iv in ledger.intervals(job)
                  if iv['category'] in goodput_lib.CONTROLLER_CATEGORIES]

    hosts: List[Dict] = []
    skew = None
    alerts: List[Dict] = []
    if store is not None:
        service = service_of(job)
        if now is None:
            # Anchor on the newest ingested interval (same postmortem
            # posture as `skytpu top`: a dead job shows its last
            # window, not an empty frame).
            now = store.last_t(service)
            now = time.time() if now is None else now
        t0, t1 = now - window, now
        by_host = store.histogram_window_by_replica(
            service, metrics_lib.TRAIN_STEP_FAMILY, t0, t1)
        res = max(store.resolution, 1e-9)
        skew_res = goodput_lib.step_time_skew(store, service, t0, t1)
        from skypilot_tpu.serve import metrics_math
        for host in sorted(h for h in by_host if h):
            # Per-interval p50 strip: one quantile per resolution
            # interval, same shape as top.py's tpot strip.
            strip = _p50_strip(store, service, host, t1,
                               min(window, 24 * res), res)
            p50 = metrics_math.quantile_from_cumulative(
                by_host[host], 0.5)
            hosts.append({'host': host, 'p50_s': p50, 'strip': strip})
        skew = skew_res
        alerts = store.active_alerts(service)

    return {
        'job': job,
        'name': (job_rec or {}).get('name'),
        'status': (job_rec or {}).get('status'),
        'recovery_count': (job_rec or {}).get('recovery_count'),
        'goodput_pct': (100.0 * totals.get(goodput_lib.PRODUCTIVE, 0.0)
                        / wall if wall > 0 else None),
        'wall_s': wall,
        'productive_s': totals.get(goodput_lib.PRODUCTIVE, 0.0),
        'badput': badput,
        'recoveries': recoveries,
        'hosts': hosts,
        'skew': skew,
        'alerts': alerts,
    }


def _p50_strip(store, service: str, host: str, t1: float,
               span: float, res: float) -> List[float]:
    from skypilot_tpu.serve import metrics_math
    strip: List[float] = []
    t = t1 - span
    while t < t1:
        cum = store.histogram_window_by_replica(
            service, metrics_lib.TRAIN_STEP_FAMILY, t, t + res
        ).get(host)
        if cum:
            q = metrics_math.quantile_from_cumulative(cum, 0.5)
            if q is not None:
                strip.append(q)
        t += res
    return strip


def _badput_bar(goodput_pct: Optional[float], width: int = 24) -> str:
    if goodput_pct is None:
        return ''
    filled = int(round(width * goodput_pct / 100.0))
    return '█' * filled + '▒' * (width - filled)


def render(snap: Dict) -> str:
    """A snapshot as the fixed-layout text frame."""
    name = f" {snap['name']}" if snap.get('name') else ''
    status = f" ({snap['status']})" if snap.get('status') else ''
    head = f"JOB {snap['job']}{name}{status}"
    gp = snap['goodput_pct']
    head += (f"  goodput {gp:.1f}%" if gp is not None
             else '  goodput --')
    head += f"  wall {snap['wall_s']:.0f}s"
    if snap.get('recovery_count') is not None:
        head += f"  recoveries {snap['recovery_count']}"
    lines = [head]
    if gp is not None:
        lines.append(f"BADPUT  {_badput_bar(gp)}  "
                     f"productive {gp:.1f}%")
    for b in snap['badput']:
        lines.append(f"  {b['category']:<20}{b['seconds']:>9.1f}s"
                     f"{b['pct']:>6.1f}%")
    if snap['hosts']:
        lines.append(f"{'HOST':<12}{'p50 STEP':>10}  TREND")
        slow = (snap['skew'] or {}).get('slow_host')
        for h in snap['hosts']:
            mark = '   <- slow' if h['host'] == slow else ''
            lines.append(
                f"{h['host']:<12}{top_lib._fmt_ms(h['p50_s']):>10}  "
                f"{top_lib.sparkline(h['strip'])}{mark}")
    if snap['skew'] is not None:
        lines.append(f"skew {snap['skew']['skew']:.2f} "
                     f"(slow {snap['skew']['slow_host']})")
    if snap['recoveries']:
        lines.append('RECOVERY TIMELINE:')
        for iv in snap['recoveries']:
            lines.append(f"  t={iv['t0']:.0f} {iv['category']} "
                         f"{iv['t1'] - iv['t0']:.1f}s")
    if snap['alerts']:
        for a in snap['alerts']:
            pool = f"[{a['pool']}]" if a['pool'] else ''
            lines.append(
                f"ALERT {a['rule']}{pool} firing since "
                f"t={a['fired_at']:.0f} (burn {a['burn']})")
    else:
        lines.append('ALERTS: none')
    return '\n'.join(lines)


def run(job: str,
        ledger: Optional[goodput_lib.GoodputLedger] = None,
        store=None,
        interval: float = 2.0,
        iterations: Optional[int] = None,
        window: float = 300.0) -> int:
    """The interactive loop; iterations=1 gives one plain frame (and
    is how a dead job's postmortem is printed)."""
    from skypilot_tpu.jobs import state as jobs_state
    shown = 0
    try:
        while iterations is None or shown < iterations:
            try:
                rec = jobs_state.get(int(job))
            except Exception:  # pylint: disable=broad-except
                rec = None  # non-numeric job key or no jobs db yet
            frame = render(snapshot(job, ledger=ledger, store=store,
                                    job_rec=rec, window=window))
            if iterations is None or iterations > 1:
                print('\033[2J\033[H', end='')
            print(frame)
            shown += 1
            if iterations is not None and shown >= iterations:
                break
            time.sleep(interval)
    except KeyboardInterrupt:
        pass
    return 0
