"""Training goodput ledger + multi-host straggler detection.

Serving got its trend layer in PR 19; this module gives training jobs
the same treatment.  Every second of a managed job's wall-clock is
classified into exactly one category — productive step time or one of
the badput buckets below — and the classification survives the two
ways training state usually dies: the *worker* process is preempted
with its slice (the trainer's in-memory recorder is gone) and the
*controller* restarts (its poll loop forgets what it was timing).
Both therefore write through to one durable ``goodput_ledger`` table
behind the pluggable state backend (sqlite + Postgres via the PR 15
dialect layer, same idiom as obs/store.py), keyed ``(job, category)``
with additive upserts — so the breakdown SUMS across recoveries and
controller restarts, and ``goodput_pct = productive / wall`` is a
number you can still compute after the job (and its cluster, and its
processes) are all gone.

Two producers write the ledger:

- the **trainer** (train/trainer.py) runs a :class:`PhaseRecorder` —
  an interval state machine over host-side ``perf_counter`` stamps
  (ZERO device syncs, zero recompile perturbation: classification
  never touches a jax value).  Coarse phases (init/XLA-compile,
  checkpoint save/restore, productive windows) are interval
  transitions; per-step input-stall time is *carved* out of the open
  productive interval without a per-step flight-recorder event, so
  the hot loop pays two ``perf_counter`` calls and a float add;
- the **jobs controller** (jobs/controller.py) writes the categories
  only it can see: ``preemption_downtime`` (preemption detected →
  recovery dispatch) and ``recovery_relaunch`` (slice delete +
  re-provision + resubmit → RUNNING again), bracketed by the
  ``jobs.preemption`` / ``jobs.recovery`` flight-recorder instants
  PR 11 already records.

Straggler detection rides the per-host step-time histograms the
trainer now exports (``skytpu_train_step_seconds{host=...}``): the
store keeps the host label through downsampling
(obs/store.py HISTOGRAM_SUB_FAMILIES) and
:func:`step_time_skew` derives max-host-p50 / median-host-p50 per
window into the ``skytpu_train_step_skew`` gauge, which the
``straggler`` alert rule (obs/alerts.train_rules) burns on.
"""
from __future__ import annotations

import os
import statistics
import time
from typing import Callable, Dict, List, Optional

from skypilot_tpu.serve import metrics_math
from skypilot_tpu.server import metrics as metrics_lib
from skypilot_tpu.server import tracing
from skypilot_tpu.utils import db_utils

# ----- categories -------------------------------------------------------------
PRODUCTIVE = 'productive'
INIT_COMPILE = 'init_compile'
CHECKPOINT_SAVE = 'checkpoint_save'
CHECKPOINT_RESTORE = 'checkpoint_restore'
INPUT_STALL = 'input_stall'
PREEMPTION_DOWNTIME = 'preemption_downtime'
RECOVERY_RELAUNCH = 'recovery_relaunch'

BADPUT_CATEGORIES = (INIT_COMPILE, CHECKPOINT_SAVE, CHECKPOINT_RESTORE,
                     INPUT_STALL, PREEMPTION_DOWNTIME, RECOVERY_RELAUNCH)
CATEGORIES = (PRODUCTIVE,) + BADPUT_CATEGORIES

# The categories only the controller can observe (the worker is dead
# while they accrue).
CONTROLLER_CATEGORIES = (PREEMPTION_DOWNTIME, RECOVERY_RELAUNCH)

# Flight-recorder span names (registered in tracing.SPAN_HELP).
PHASE_SPAN = 'train.phase'
DOWNTIME_SPAN = 'jobs.downtime'
# Recorder rid when the trainer runs outside a managed job.
TRAIN_RID = 'train-goodput'

# A trainer launched by a managed job finds its ledger identity here
# (the task's run command exports it; tests set it directly).
JOB_ENV = 'SKYTPU_GOODPUT_JOB'

_DDL = [
    # Additive per-(job, category) accumulator: the durable headline.
    """CREATE TABLE IF NOT EXISTS goodput_ledger (
        job TEXT NOT NULL,
        category TEXT NOT NULL,
        seconds REAL NOT NULL,
        intervals INTEGER NOT NULL,
        updated_at REAL NOT NULL,
        PRIMARY KEY (job, category))""",
    # Individual wall-clock intervals (recovery timeline feedstock for
    # `skytpu jobs top` postmortems — the flight-recorder ring dies
    # with its process; these rows do not).
    """CREATE TABLE IF NOT EXISTS goodput_intervals (
        job TEXT NOT NULL,
        category TEXT NOT NULL,
        t0 REAL NOT NULL,
        t1 REAL NOT NULL,
        PRIMARY KEY (job, category, t0))""",
]


def jobs_dsn() -> str:
    """The ledger's default home: the managed-jobs control-plane store
    (shared Postgres when SKYTPU_DB_URL is set, per-host sqlite
    otherwise) — the controller and `jobs top` already read it."""
    return db_utils.control_plane_dsn('SKYTPU_JOBS_DB',
                                      '~/.skytpu/managed_jobs.db')


class GoodputLedger:
    """The durable (job, category) -> seconds accumulator.

    Cheap to construct (schema creation is memoized by
    db_utils.ensure_schema); every write is one small transaction, so
    two producers (trainer on the task cluster, controller on the
    control plane) can add concurrently without coordination — the
    upsert is additive and they never write the same category."""

    def __init__(self, dsn: Optional[str] = None) -> None:
        self.dsn = dsn or jobs_dsn()

    def _ensure(self) -> str:
        db_utils.ensure_schema(self.dsn, _DDL)
        return self.dsn

    def add(self, job: str, category: str, seconds: float,
            t0: Optional[float] = None, t1: Optional[float] = None,
            now: Optional[float] = None) -> None:
        """Accumulate ``seconds`` into (job, category); when the
        interval's wall-clock bounds are known, also keep the interval
        row (timeline evidence).  Zero/negative durations are dropped
        — the recorder's tiling arithmetic never produces them, and a
        skipped empty interval cannot create a gap (its neighbours
        share the boundary stamp)."""
        if category not in CATEGORIES:
            raise ValueError(f'unknown goodput category: {category!r}')
        if seconds <= 0:
            return
        now = time.time() if now is None else now
        dsn = self._ensure()
        with db_utils.transaction(dsn) as conn:
            conn.execute(
                'INSERT INTO goodput_ledger '
                '(job, category, seconds, intervals, updated_at) '
                'VALUES (?,?,?,1,?) '
                'ON CONFLICT(job, category) DO UPDATE SET '
                'seconds = goodput_ledger.seconds + excluded.seconds, '
                'intervals = goodput_ledger.intervals + 1, '
                'updated_at = excluded.updated_at',
                (str(job), category, float(seconds), now))
            if t0 is not None and t1 is not None and t1 > t0:
                conn.execute(
                    'INSERT INTO goodput_intervals (job, category, t0, t1) '
                    'VALUES (?,?,?,?) '
                    'ON CONFLICT(job, category, t0) DO NOTHING',
                    (str(job), category, float(t0), float(t1)))

    # ----- queries ------------------------------------------------------------
    def totals(self, job: str) -> Dict[str, float]:
        return {r['category']: float(r['seconds'])
                for r in db_utils.query(
                    self._ensure(),
                    'SELECT category, seconds FROM goodput_ledger '
                    'WHERE job=?', (str(job),))}

    def wall(self, job: str) -> float:
        """Total classified wall-clock (the categories tile it)."""
        return sum(self.totals(job).values())

    def goodput_pct(self, job: str) -> Optional[float]:
        totals = self.totals(job)
        wall = sum(totals.values())
        if wall <= 0:
            return None
        return 100.0 * totals.get(PRODUCTIVE, 0.0) / wall

    def downtime_s(self, job: str) -> float:
        """Cumulative recovery cost: the controller-observed
        categories (the `jobs queue` DOWNTIME column)."""
        totals = self.totals(job)
        return sum(totals.get(c, 0.0) for c in CONTROLLER_CATEGORIES)

    def downtime_by_job(self) -> Dict[str, float]:
        """One query for the whole queue listing."""
        out: Dict[str, float] = {}
        marks = ','.join('?' * len(CONTROLLER_CATEGORIES))
        for r in db_utils.query(
                self._ensure(),
                f'SELECT job, SUM(seconds) AS s FROM goodput_ledger '
                f'WHERE category IN ({marks}) GROUP BY job',
                tuple(CONTROLLER_CATEGORIES)):
            out[r['job']] = float(r['s'])
        return out

    def intervals(self, job: str, category: Optional[str] = None
                  ) -> List[Dict]:
        sql = ('SELECT category, t0, t1 FROM goodput_intervals '
               'WHERE job=?')
        params: list = [str(job)]
        if category is not None:
            sql += ' AND category=?'
            params.append(category)
        sql += ' ORDER BY t0'
        return [{'category': r['category'], 't0': float(r['t0']),
                 't1': float(r['t1'])}
                for r in db_utils.query(self._ensure(), sql,
                                        tuple(params))]

    def jobs(self) -> List[str]:
        return [r['job'] for r in db_utils.query(
            self._ensure(),
            'SELECT DISTINCT job FROM goodput_ledger ORDER BY job')]


class PhaseRecorder:
    """In-process wall-clock classifier: at any instant exactly ONE
    category is open, so the closed intervals tile elapsed time with
    no gaps and no overlaps *by construction* — ``sum(totals) ==
    last_boundary - first_boundary`` exactly (the tiling property
    tests/test_goodput.py fuzzes).

    Two attribution mechanisms, matched to their cost budgets:

    - :meth:`begin` — a phase transition: closes the open interval
      (flight-recorder span + optional ledger write) and opens the
      next.  Used at coarse boundaries only (init→productive,
      checkpoint save, log-window roll), so the durable writes stay
      off the per-step path;
    - :meth:`carve` — re-attributes seconds *within* the open interval
      to another category (per-step input-stall time) without a span
      or db write: a dict add on the hot loop, settled when the
      interval closes.  Carves are clamped so they can never exceed
      the interval they were carved from (tiling survives a lying
      clock).
    """

    def __init__(self, job: str = '',
                 ledger: Optional[GoodputLedger] = None,
                 rid: Optional[str] = None,
                 clock: Optional[Callable[[], float]] = None,
                 to_wall: Optional[Callable[[float], float]] = None
                 ) -> None:
        self.job = str(job or '')
        self.ledger = ledger if self.job else None
        self.rid = rid or (f'job-{self.job}' if self.job else TRAIN_RID)
        self._clock = clock or time.perf_counter
        # perf_counter stamps render in wall time via the tracing
        # anchor; an injected (sim) clock is its own wall time.
        if to_wall is not None:
            self._to_wall = to_wall
        elif clock is None:
            self._to_wall = tracing.wall_of
        else:
            self._to_wall = lambda t: t
        self.totals: Dict[str, float] = {}
        self._cat: Optional[str] = None
        self._t0: Optional[float] = None
        self._carves: Dict[str, float] = {}

    @classmethod
    def from_env(cls) -> 'PhaseRecorder':
        """The trainer's default: a managed job exports SKYTPU_GOODPUT_JOB
        and gets durable accumulation; anything else records locally
        (gauges + flight recorder only)."""
        job = os.environ.get(JOB_ENV, '').strip()
        return cls(job=job, ledger=GoodputLedger() if job else None)

    def now(self) -> float:
        return self._clock()

    @property
    def category(self) -> Optional[str]:
        return self._cat

    def begin(self, category: str, now: Optional[float] = None) -> None:
        """Close the open interval (if any) at ``now`` and open
        ``category``.  Re-beginning the same category rolls the
        interval — the flush point for long productive windows."""
        if category not in CATEGORIES:
            raise ValueError(f'unknown goodput category: {category!r}')
        now = self.now() if now is None else now
        self._close_open(now)
        self._cat = category
        self._t0 = now
        self._carves = {}

    def carve(self, category: str, seconds: float) -> None:
        """Attribute ``seconds`` of the OPEN interval to ``category``
        instead of the interval's own; settled (clamped to the
        interval's duration) at close.  Hot-loop safe: no span, no db,
        no lock."""
        if self._cat is None or seconds <= 0:
            return
        self._carves[category] = self._carves.get(category, 0.0) \
            + seconds

    def close(self, now: Optional[float] = None) -> Dict[str, float]:
        """Close the open interval and return the final totals."""
        now = self.now() if now is None else now
        self._close_open(now)
        return dict(self.totals)

    def _close_open(self, now: float) -> None:
        if self._cat is None:
            return
        dur = max(0.0, now - self._t0)
        attrs: Dict[str, float] = {}
        carved = 0.0
        for cat, sec in self._carves.items():
            sec = min(sec, dur - carved)
            if sec <= 0:
                continue
            carved += sec
            self.totals[cat] = self.totals.get(cat, 0.0) + sec
            attrs[f'{cat}_s'] = round(sec, 6)
            if self.ledger is not None:
                self.ledger.add(self.job, cat, sec)
        main = dur - carved
        self.totals[self._cat] = self.totals.get(self._cat, 0.0) + main
        if self.ledger is not None:
            self.ledger.add(self.job, self._cat, main,
                            t0=self._to_wall(self._t0),
                            t1=self._to_wall(now))
        tracing.record_span(self.rid, PHASE_SPAN, self._t0, now,
                            category=self._cat, **attrs)
        self._cat = None
        self._t0 = None
        self._carves = {}

    # ----- live views (open interval included) --------------------------------
    def snapshot(self, now: Optional[float] = None) -> Dict[str, float]:
        """Totals as-if the open interval closed at ``now`` — without
        closing it (no span, no db write): the gauge-export view."""
        snap = dict(self.totals)
        if self._cat is not None:
            now = self.now() if now is None else now
            dur = max(0.0, now - self._t0)
            carved = 0.0
            for cat, sec in self._carves.items():
                sec = min(sec, dur - carved)
                if sec <= 0:
                    continue
                carved += sec
                snap[cat] = snap.get(cat, 0.0) + sec
            snap[self._cat] = snap.get(self._cat, 0.0) + (dur - carved)
        return snap

    def productive_s(self, now: Optional[float] = None) -> float:
        """Productive seconds including the open interval's elapsed
        share — the denominator of badput-aware throughput."""
        return self.snapshot(now).get(PRODUCTIVE, 0.0)

    def goodput_pct(self, now: Optional[float] = None
                    ) -> Optional[float]:
        snap = self.snapshot(now)
        wall = sum(snap.values())
        if wall <= 0:
            return None
        return 100.0 * snap.get(PRODUCTIVE, 0.0) / wall


# ----- straggler detection ----------------------------------------------------
def step_time_skew(store, service: str, t0: float, t1: float,
                   q: float = 0.5) -> Optional[Dict]:
    """Per-host step-time skew over ``(t0, t1]``: max-host p50 over
    median-host p50 from the host-labeled step histograms the store
    keeps (HISTOGRAM_SUB_FAMILIES).  None below two reporting hosts —
    a single host has no skew, and a dead scrape must not read as
    'balanced'."""
    by_host = store.histogram_window_by_replica(
        service, metrics_lib.TRAIN_STEP_FAMILY, t0, t1)
    p50s: Dict[str, float] = {}
    for host, cum in by_host.items():
        if not host:
            continue  # unlabeled legacy series: no host attribution
        v = metrics_math.quantile_from_cumulative(cum, q)
        if v is not None and v > 0:
            p50s[host] = v
    if len(p50s) < 2:
        return None
    med = statistics.median(p50s.values())
    if med <= 0:
        return None
    slow_host = max(p50s, key=lambda h: p50s[h])
    return {
        'skew': p50s[slow_host] / med,
        'slow_host': slow_host,
        'p50_by_host': p50s,
    }


def evaluate_stragglers(store, service: str,
                        now: Optional[float] = None,
                        window: Optional[float] = None
                        ) -> Optional[Dict]:
    """Controller-side skew tick: derive the window's skew, export it
    as the ``skytpu_train_step_skew`` gauge AND write it into the
    store (a derived gauge row), so the `straggler` alert rule burns
    on the same number `jobs top` renders."""
    if now is None:
        now = store.last_t(service)
        now = time.time() if now is None else now
    if window is None:
        window = max(60.0, 6.0 * store.resolution)
    res = step_time_skew(store, service, now - window, now)
    if res is None:
        return None
    metrics_lib.set_gauge(metrics_lib.TRAIN_STEP_SKEW_FAMILY,
                          res['skew'], service=service)
    store.put_gauge(service, metrics_lib.TRAIN_STEP_SKEW_FAMILY,
                    res['skew'], now)
    return res


def train_obs_tick(store, service: str, exposition: str, now: float,
                   engine=None, roles: Optional[Dict[str, str]] = None
                   ) -> Optional[Dict]:
    """One controller tick for a training job, mirroring the serve
    controller's `_obs_tick`: ingest the workers' federated scrape,
    derive the skew gauge, evaluate the train alert rules.  Returns
    the skew result (None when skew is not derivable this tick)."""
    skew = None
    if store.ingest(service, exposition, now=now, roles=roles):
        skew = evaluate_stragglers(store, service, now=now)
        if engine is not None:
            engine.evaluate(now)
    return skew
