"""Logger setup (parity: sky/sky_logging.py)."""
from __future__ import annotations

import logging
import os
import sys

_FORMAT = '%(levelname).1s %(asctime)s %(name)s:%(lineno)d] %(message)s'
_DATE_FORMAT = '%m-%d %H:%M:%S'
_initialized = False


def _init_root() -> None:
    global _initialized
    if _initialized:
        return
    root = logging.getLogger('skypilot_tpu')
    level_name = os.environ.get('SKYTPU_LOG_LEVEL', 'INFO').upper()
    root.setLevel(getattr(logging, level_name, logging.INFO))
    if not root.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT, _DATE_FORMAT))
        root.addHandler(handler)
    root.propagate = False
    _initialized = True


def init_logger(name: str) -> logging.Logger:
    _init_root()
    return logging.getLogger(name)
