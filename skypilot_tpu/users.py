"""User identity + RBAC (capability parity: sky/users/ — rbac.py roles,
permission.py checks; identity columns on state rows as in
sky/global_user_state.py user_hash).

Identity is ambient: ``SKYTPU_USER`` env (or the OS login), overridable
per-request on the server (the SDK forwards the caller's identity in the
``X-SkyTPU-User`` header).  Roles come from the layered config:

    users:
      alice: admin
      bob: user

RBAC activates only when a ``users:`` section exists — with none, every
caller is admin and nothing is restricted (single-user/library use).
When active, non-admins may only mutate clusters they own; reads stay
workspace-scoped but unrestricted by role.  Identity is trusted from the
authenticated channel (the bearer token gates the API; the reference
similarly trusts its auth proxy's user header, sky/server/server.py).
"""
from __future__ import annotations

import contextlib
import dataclasses
import getpass
import os
import threading
from typing import Any, Dict, Iterator, Optional

from skypilot_tpu import exceptions

ADMIN = 'admin'
USER = 'user'

_local = threading.local()


@dataclasses.dataclass(frozen=True)
class User:
    name: str
    role: str


def _configured_roles() -> Optional[Dict[str, str]]:
    from skypilot_tpu import sky_config
    roles = sky_config.get_nested(('users',), None)
    if roles is None:
        return None
    return {str(k): str(v) for k, v in roles.items()}


def rbac_enabled() -> bool:
    return _configured_roles() is not None


def current_user() -> User:
    """The acting user: per-request override > env > OS login."""
    name = getattr(_local, 'override_name', None)
    if name is None:
        name = os.environ.get('SKYTPU_USER')
    if name is None:
        try:
            name = getpass.getuser()
        except Exception:  # pylint: disable=broad-except
            name = 'unknown'
    roles = _configured_roles()
    if roles is None:
        role = ADMIN                     # RBAC off: nobody is restricted
    else:
        role = roles.get(name, USER)
    if role not in (ADMIN, USER):
        raise exceptions.InvalidSkyConfigError(
            f'users.{name}: role must be admin or user, got {role!r}')
    return User(name=name, role=role)


@contextlib.contextmanager
def override(name: Optional[str]) -> Iterator[None]:
    """Act as `name` within this thread (server per-request identity)."""
    prev = getattr(_local, 'override_name', None)
    _local.override_name = name
    try:
        yield
    finally:
        _local.override_name = prev


def check_cluster_op(record: Dict[str, Any], operation: str) -> None:
    """Non-admins may only mutate their own clusters."""
    user = current_user()
    if user.role == ADMIN:
        return
    owner = record.get('user_name')
    if owner is not None and owner != user.name:
        raise exceptions.PermissionDeniedError(
            f'{operation} on cluster {record["name"]!r} denied: owned by '
            f'{owner!r}, you are {user.name!r} (role {user.role})')
