"""Continuous-batching decode engine (JetStream twin).

The reference's serving baseline is JetStream driven through a recipe
YAML (examples/tpu/v6e/serve-llama2-7b.yaml; numbers at
examples/tpu/v6e/README.md:119-127).  This is the first-party TPU-native
equivalent, built on the same architecture JetStream proved out:

- a fixed pool of decode *slots*; every decode call is ONE jitted
  dispatch over the whole [n_slots] batch (batched matmuls keep the MXU
  busy and amortize the HBM weight sweep — decode is bandwidth-bound, so
  tokens/s scales almost linearly with occupied slots);
- each dispatch runs `steps_per_call` decode steps under `lax.scan`, so
  the host<->device round-trip (which can be ~100 ms on tunneled control
  planes) is amortized over T tokens per slot, not paid per token;
- the engine performs exactly ONE device->host sync per step: last
  tokens and lengths live on device, prefill+insert is a single fused
  dispatch whose sampled first token stays on device, and the decode
  call returns [T+1, n_slots] with row 0 = each slot's previously
  sampled token — so a freshly admitted request's first token rides the
  same fetch as the decode tokens;
- prefill runs per-request at bucket-padded lengths (few distinct
  compiled shapes), then the request's KV cache is *inserted* into its
  slot of the big cache in one device-side copy;
- the host loop only orchestrates: admit prefills into free slots, call
  the decode step, stream sampled tokens out, retire finished slots.
  Tokens a slot produces past its own EOS/max within a multi-step call
  are discarded host-side (bounded waste, never wrong output: a retiring
  slot's cache is fully overwritten by the next insert).

Static shapes throughout: the decode step never recompiles, prompts
compile once per bucket.  Slot safety relies on the model cache's
invariant (models/llama.py _decode_attend): attention masks k_pos >
q_pos, and inserts overwrite a slot's whole cache, so a reused slot never
leaks its previous request's KV.

Long prompts (chunked prefill): prompts longer than the largest bucket
no longer fuse into one dispatch — they stream through a per-request
SCRATCH cache in bucket-sized chunks, one chunk dispatched per loop
iteration between decode calls, so a 128k prefill delays the in-flight
decode batch by at most ONE chunk instead of monopolizing the device.
Each chunk writes its K/V at absolute positions and attends over the
accumulated cache (models/llama.py _decode_attend S>1); the final chunk
samples the prompt's first token and scatters the scratch cache into
the request's slot in the same dispatch — from there the request is
indistinguishable from a bucket-admitted one.  Prompts up to
`max_seq_len - 1` (or the `max_prompt_len` knob) are admissible.
Prompts that fit one bucket keep the fused single-dispatch path
byte-for-byte, so short-prompt bench numbers are untouched.

Paged KV + prefix caching (`kv_page_size`): the slot-contiguous cache
becomes a page POOL [n_pages, H, page_size, D] with host-side per-slot
page tables (inference/paging.py owns the allocator + radix trie).
Admission charges ceil((prompt+max_new)/page) pages instead of
reserving n_slots x max_seq_len of HBM, and requests sharing a
page-aligned token prefix (system prompts, few-shot templates,
multi-turn replays — retire donates prompt+generated pages) reference
the prefilled pages instead of recomputing them: the matched pages
gather into the chunked-prefill scratch and only the suffix prefills.
Shared pages are never written (extension allocates fresh pages, so
copy-on-extend is free), eviction is LRU over pages no live slot
holds, and every contract above survives: decode stays one jitted
dispatch + one sync per step (tables ship async, only when dirty),
programs never recompile (tables are data, not shapes), and greedy
output is token-identical to the unpaged engine — single-device and
tensor-parallel (the pool shards over kv heads like the dense cache).

Weight swaps (`update_params`) are double-buffered and in-flight-safe:
the new tree is STAGED into the engine's committed layouts/shardings
(device_put overlaps with serving), INSTALLED at the loop's next
dispatch boundary, and the old buffers are RELEASED once the last call
dispatched against them has retired — no drain, serving never stops.
This is what rolling weight refresh and the RL rollout/update
alternation ride on.

Disaggregated prefill/decode (`submit_prefill` / `submit_adopt`, paged
engines only): pages are the KV-transfer unit.  A PREFILL-role request
rides the ordinary admission/chunk/prefix-hit machinery with a
one-token budget — the sampled first token arrives exactly as any
other request's — and at retire its pages are gathered off the pool
(one extra device call, synced by the SERVER thread, never the loop)
into a transferable payload (inference/kv_transfer.py) instead of
vanishing.  A DECODE-role engine adopts the payload: pages scatter
into its own pool at page granularity (one fixed-shape dispatch, no
per-token recompute), the slot starts at `length=prompt_len` with the
sampled token as its last token, and from there the request is
indistinguishable from one prefilled locally — greedy output is
token-identical to monolithic serving.  Both paths keep the
one-sync-per-step and zero-recompile contracts: export/adopt programs
have one compiled shape each, and all new bookkeeping is host state.

Tensor parallelism (13B-70B serving): pass `EngineConfig(mesh=...)`
(parallel/mesh.py build_serve_mesh) and every program above runs
mesh-sharded — params via the model's logical-axis annotations
(attention heads / MLP hidden / vocab split over the tensor axis,
everything else replicated), the per-layer KV cache
[n_slots, n_kv_heads, max_seq_len, head_dim] over its kv-heads dim, and
the jitted prefill_insert/decode programs pinned to those NamedShardings
so XLA inserts the one all-reduce per projection block that megatron-
style TP implies.  Engine state that the host reads (last tokens,
lengths, the [T+1, n_slots] output) stays replicated: the host loop is
IDENTICAL under a mesh — same one sync per step, same pipelining, same
slot bookkeeping.  `mesh=None` is the exact single-device path
(including the TPU layout pinning below), byte-for-byte unchanged.
"""
from __future__ import annotations

import collections
import dataclasses
import os
import queue
import threading
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from skypilot_tpu import sky_logging
from skypilot_tpu.inference import kv_quant
from skypilot_tpu.inference.paging import TRASH_PAGE, PagePool, RadixCache
from skypilot_tpu.perf import compile_telemetry
from skypilot_tpu.perf import cost_model as cost_model_lib
from skypilot_tpu.server import metrics as metrics_lib
from skypilot_tpu.server import tracing

logger = sky_logging.init_logger(__name__)


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    n_slots: int = 8
    # Prompt lengths are padded up to one of these (each bucket compiles
    # once).  Longest bucket bounds admissible prompts.
    prefill_buckets: tuple = (32, 64, 128, 256, 512)
    # Decode steps per jitted dispatch (lax.scan trip count).  Larger
    # values amortize host<->device latency; smaller values tighten the
    # admission/streaming granularity.
    steps_per_call: int = 8
    eos_id: Optional[int] = None       # None: never stop on a token
    temperature: float = 0.0           # 0 => greedy
    seed: int = 0
    # Admission cap for prompts.  None: anything up to max_seq_len - 1
    # is admissible (prompts beyond the largest bucket go through
    # chunked prefill).  Deployments set a lower cap to bound the
    # per-request prefill work a single caller can demand.
    max_prompt_len: Optional[int] = None
    # Tensor parallelism: a jax.sharding.Mesh whose `tensor_axis` names
    # the axis attention heads / MLP hidden shard over (build one with
    # parallel/mesh.py build_serve_mesh).  None = single-device engine.
    mesh: Optional[Any] = None
    tensor_axis: str = 'tensor'
    # Paged KV cache: break the slot-contiguous [n_slots, H, max_seq_len,
    # D] cache into fixed-size pages with a per-slot page table.
    # Admission then charges PAGES (ceil((prompt+max_new)/page) of them)
    # instead of reserving max_seq_len per slot, and shared prompt
    # prefixes are prefilled once and referenced by every matching
    # request (prefix_cache below).  Must divide every prefill bucket
    # and max_seq_len.  None = the legacy contiguous layout, unchanged.
    kv_page_size: Optional[int] = None
    # Page-pool size.  None = full backing (n_slots * max_seq_len /
    # page_size, + 1 trash page): paging with zero admission risk.
    # Deployments whose requests use less than max_seq_len set it lower
    # — that is the HBM-per-slot win.  Must fit at least one
    # max-length request (max_seq_len / page_size pages + trash).
    kv_pages: Optional[int] = None
    # Radix prefix cache over the page pool (kv_page_size set): retired
    # and admitted sequences donate their full pages to a token-keyed
    # radix trie; a new prompt sharing a page-aligned prefix skips its
    # prefill and references the cached pages (LRU-evicted when the
    # pool runs short).  Ignored without paging.
    prefix_cache: bool = True
    # KV cache element type for the paged pool: 'bf16' keeps the model
    # dtype; 'int8' quantizes pages at scatter time (symmetric absmax
    # along head_dim, one f32 scale per position — kv_quant.QuantPages)
    # and dequantizes inside the attention gather, halving decode's
    # dominant HBM stream.  Requires kv_page_size.
    kv_dtype: str = 'bf16'
    # Self-speculative decoding: draft length k per slot.  0 = off.
    # A host-side n-gram proposer drafts k tokens per slot from its own
    # history; ONE fixed-shape verify dispatch (the chunked S = k+1
    # position-scatter path) scores all drafts and accepts the longest
    # greedy-matching prefix — lossless under greedy sampling, so
    # outputs are token-identical to speculation-off.  Requires
    # kv_page_size (rejected rows land in slot-owned/trash pages) and
    # temperature == 0.0.
    speculation: int = 0


@dataclasses.dataclass
class Request:
    prompt_ids: List[int]
    max_new_tokens: int
    out: 'queue.Queue[Optional[int]]' = dataclasses.field(
        default_factory=queue.Queue)
    submitted_at: float = dataclasses.field(default_factory=time.perf_counter)
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    emitted: int = 0
    # Distributed-tracing id (honored from the HTTP layer's
    # X-Skytpu-Request-Id).  None = untraced (library-direct callers
    # that did not opt in); the engine records flight-recorder span
    # events only for traced requests.
    request_id: Optional[str] = None
    # perf_counter stamp of the END of this request's last prefill
    # dispatch: the engine.dispatch span (prefill end -> first token)
    # starts here, so the TTFT decomposition tiles exactly.
    prefill_end_at: Optional[float] = None
    # Set when a stuck-pool spill demoted this request to a full
    # prefill: re-matching it would just re-pin the pages that starved
    # the pool (see _spill_stuck_hits).
    no_prefix: bool = False
    # Disaggregated serving (paged engines only).  export=True marks a
    # prefill-role request (submit_prefill): it runs with a one-token
    # budget and, at retire, its prompt pages + sampled first token
    # are gathered into `kv_export` for kv_transfer serialization
    # instead of being dropped.  `downstream_max_new` is the token
    # budget the DECODE replica will serve (travels in the payload;
    # this engine never decodes it).  `adopt` carries a decode-role
    # request's incoming state: (first_token, kv leaves as host numpy
    # [n_kv_pages, H, page_size, D] in cache-tree leaf order).
    export: bool = False
    downstream_max_new: int = 0
    kv_export: Optional[dict] = None
    adopt: Optional[tuple] = None

    def tokens(self) -> List[int]:
        """Drain: block until the request finishes, return all tokens."""
        toks = []
        while True:
            t = self.out.get()
            if t is None:
                return toks
            toks.append(t)


class _Slot:
    __slots__ = ('request', 'length', 'first_pending', 'done', 'pages',
                 'n_shared', 'toks')

    def __init__(self, request: Request, length: int,
                 pages: Optional[List[int]] = None,
                 n_shared: int = 0) -> None:
        self.request = request
        self.length = length              # prompt len + emitted (host view)
        # True until the prefill-sampled first token has been emitted
        # (it arrives as row 0 of the next decode call's output).
        self.first_pending = True
        # Finished (retired); set on the SLOT object so a pipelined
        # in-flight call's snapshot can tell "emit this slot's remaining
        # rows" (handoff: a successor was admitted into the slot index)
        # from "this slot's rows are retire-lag garbage".
        self.done = False
        # Paged engine: the KV pages backing this slot, in logical page
        # order; the first n_shared are prefix-cache pages this slot
        # references but never writes.  Released (and the full ones
        # donated to the radix cache) at retire.
        self.pages = pages
        self.n_shared = n_shared
        # Emitted tokens (prefix_cache only): retire donates the pages
        # covering prompt+generated, so multi-turn replays hit.
        self.toks: List[int] = []


class _ChunkedPrefill:
    """Host state of one long prompt mid-chunked-prefill: the scratch
    cache accumulating its K/V and how far into the prompt it is.  A
    prefix-cache hit starts with offset == the matched length and a
    scratch pre-seeded by gathering the shared pages."""
    __slots__ = ('request', 'scratch', 'offset', 'last_chunk_end',
                 'shared_pages')

    def __init__(self, request: Request, scratch,
                 offset: int = 0,
                 shared_pages: Optional[List[int]] = None) -> None:
        self.request = request
        self.scratch = scratch
        self.offset = offset     # prompt tokens already in the scratch
        # perf_counter end stamp of the previous chunk dispatch: chunk
        # span k runs [chunk k-1 end, chunk k end], so the per-chunk
        # spans tile the whole chunked-prefill phase (the interleaved
        # decode delay lands inside the chunk that waited behind it).
        self.last_chunk_end: Optional[float] = None
        # Prefix-cache pages this request references (already ref'd on
        # its behalf by the match); they become the head of its slot's
        # page table at insert time.
        self.shared_pages = shared_pages or []


def _ngram_continuation(hist: List[int], k: int, max_ngram: int = 3,
                        window: int = 512) -> np.ndarray:
    """Self-speculative n-gram draft: the k tokens that followed the
    most recent earlier occurrence of ``hist``'s tail n-gram (longest
    n first, n = max_ngram..1), zero-padded when the match runs out.

    Pure host arithmetic over the slot's own token history — no second
    model, no device work.  On repetitive traffic (code, templated
    text, multi-turn replays) the continuation after a repeated n-gram
    is usually the same continuation, which is exactly what verify
    accepts; on incompressible traffic drafts self-reject to m=1 and
    the engine degrades to plain (correct) decode.  Only the last
    ``window`` tokens are scanned: a bounded O(window * max_ngram)
    per slot per step, never proportional to the full context.
    """
    out = np.zeros((k,), np.int32)
    ln = len(hist)
    if ln < 2:
        return out
    lo = max(0, ln - window)
    for n in range(min(max_ngram, ln - 1), 0, -1):
        tail = hist[ln - n:]
        # Most recent earlier occurrence: scan ends before the tail
        # itself (i + n < ln) so the draft continues PAST the match.
        for i in range(ln - n - 1, lo - 1, -1):
            if hist[i:i + n] == tail:
                # When the match overlaps the tail (a cycling stream —
                # the case speculation wins hardest on), the observed
                # continuation is shorter than k; extend it cyclically
                # instead of zero-padding, so a period-p loop drafts
                # the whole next k tokens, not just p of them.
                span = ln - (i + n)
                for j in range(k):
                    out[j] = hist[i + n + (j if j < span else j % span)]
                return out
    return out


class DecodeEngine:
    """Slot-based continuous batching over a Llama-family model.

    `model.cfg.max_seq_len` bounds prompt+generation; the per-layer KV
    cache is [n_slots, n_kv_heads, max_seq_len, head_dim].
    """

    def __init__(self, model, params, config: EngineConfig = EngineConfig()):
        self.model = model
        self.params = params
        if config.n_slots <= 0:
            raise ValueError(
                f'EngineConfig.n_slots must be a positive slot count, '
                f'got {config.n_slots}')
        # Buckets beyond the cache length can never be inserted; drop
        # them so submit() rejects oversized prompts up front instead of
        # crashing the loop thread at dynamic_update_slice time.
        max_len = model.cfg.max_seq_len
        buckets = tuple(b for b in config.prefill_buckets if b <= max_len)
        if not buckets:
            buckets = (max_len,)
        config = dataclasses.replace(config, prefill_buckets=buckets)
        self._validate_paging(config, max_len)
        self.cfg = config
        self._rng = jax.random.PRNGKey(config.seed)
        self._prefill_q: 'queue.Queue[Request]' = queue.Queue()
        # Orders submit()'s error-check-then-enqueue against the crash
        # path's set-error-then-drain: without it a request enqueued
        # between those two drain steps is never failed and its tokens()
        # blocks forever.
        self._submit_lock = threading.Lock()
        self._slots: List[Optional[_Slot]] = [None] * config.n_slots
        # In-flight decode call (pipelined loop): (device out, snapshot
        # of the slots it covers).  Processed one iteration later.
        self._inflight = None
        # Long prompts (beyond the largest bucket) queue here and go
        # through chunked prefill, one at a time.
        self._long_q: 'queue.Queue[Request]' = queue.Queue()
        self._chunked: Optional[_ChunkedPrefill] = None
        self._scratch_fn = None
        # Paged KV cache (kv_page_size set): host allocator + per-slot
        # page tables + (optionally) the radix prefix cache.  All page
        # bookkeeping is loop-thread state; only the table itself is
        # shipped to device (async H2D, refreshed when dirty).
        self._paged = config.kv_page_size is not None
        self._kv_quant = self._paged and config.kv_dtype == 'int8'
        self._spec_k = config.speculation if self._paged else 0
        self._page_size = config.kv_page_size
        self._pages_per_slot = (max_len // config.kv_page_size
                                if self._paged else 0)
        self._pool_alloc: Optional[PagePool] = None
        self._radix: Optional[RadixCache] = None
        self._page_tables = None        # host np [n_slots, pages_per_slot]
        self._pt_device = None
        self._pt_dirty = True
        # Short prompts pulled off _prefill_q by the loop, awaiting page
        # reservation (head-of-line on allocation failure); prefix-cache
        # hits divert here to ride the chunk machinery.
        self._ready_q: 'collections.deque' = collections.deque()
        self._hit_q: 'collections.deque' = collections.deque()
        # Disaggregated serving: incoming KV-handoff adoptions (decode
        # role).  Submitted into _adopt_q by the HTTP layer; the loop
        # drains them into _adopt_ready and admits head-of-line as
        # slots + pages free up (same retry discipline as _ready_q).
        self._adopt_q: 'queue.Queue[Request]' = queue.Queue()
        self._adopt_ready: 'collections.deque' = collections.deque()
        if self._paged:
            n_pages = (config.kv_pages if config.kv_pages is not None
                       else config.n_slots * self._pages_per_slot + 1)
            self._pool_alloc = PagePool(n_pages, config.kv_page_size)
            if config.prefix_cache:
                self._radix = RadixCache(self._pool_alloc)
            self._page_tables = np.full(
                (config.n_slots, self._pages_per_slot), TRASH_PAGE,
                np.int32)
        # Prompt tokens accepted but not yet prefilled (queued requests
        # + the un-prefilled remainder of the active chunked prompt).
        # Writers hold _submit_lock; the loop's gauge read is a bare
        # GIL-atomic int read (a one-iteration-stale value is harmless,
        # and the idle loop must not take the lock every millisecond).
        self._queued_tokens = 0
        # Double-buffered weight swap: update_params stages here; the
        # loop installs at its next dispatch boundary and retires the
        # old tree once no dispatched call references it.
        self._params_lock = threading.Lock()
        self._staged_params: Optional[tuple] = None
        self._retiring_params: List[Any] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_gauges: Optional[tuple] = None
        # Device-cost attribution (perf/): the static cost model is
        # built once the cache exists (dtype of the page pool is an
        # input); the loop thread folds these host-side accumulators
        # into the live MFU / bytes-per-token gauges — token count,
        # token-weighted context length and token-weighted batch
        # occupancy, all written by _process_rows.
        self._cost_model: Optional[cost_model_lib.EngineCostModel] = None
        self._perf_tokens = 0
        self._perf_ctx_sum = 0
        self._perf_occ_sum = 0
        self._perf_window: Optional[tuple] = None
        self._perf_last: Optional[dict] = None
        # Minimum attribution window; benchmarks/tests shrink or grow
        # it to bracket exactly their measured region.
        self.perf_window_s = float(
            os.environ.get('SKYTPU_PERF_WINDOW_S', '0.5'))
        self.error: Optional[BaseException] = None
        self._fmt_params = None
        self._prefill_compiled: Dict[tuple, Any] = {}
        self._chunk_compiled: Dict[tuple, Any] = {}
        # Mesh-sharded serving state (None on the single-device path).
        self._mesh = config.mesh
        self._param_shardings = None
        self._cache_shardings = None
        self._repl = None
        self._scratch_shardings = None
        if self._mesh is not None:
            self._setup_mesh()
        # True when the installed tree is an engine-private device copy
        # (mesh/TPU-layout device_put) that update_params may DELETE
        # after a swap; on the plain path the tree is the caller's and
        # is only ever dereferenced.
        self._params_owned = self._mesh is not None
        self._build_fns()
        self._init_cache()
        if (jax.default_backend() == 'tpu' and self._mesh is None and
                not self._paged):
            # The AOT layout pass is specialized to the contiguous
            # cache; the paged pool rides default layouts (its decode
            # gathers re-tile anyway).
            try:
                self._optimize_layouts()
            except Exception:  # pylint: disable=broad-except
                # Degraded but functional: decode relays out weights as
                # HLO temps (extra HBM). Big models may OOM — but never
                # refuse to serve because a layout API changed.
                logger.exception('param layout optimization failed; '
                                 'serving with default layouts')
                self._fmt_params = None
        # Cost model + compile telemetry.  from_engine_state reads only
        # leaf METADATA (shape/dtype — the page pool's dtype is how a
        # future int8 KV cache lands as a measured bytes/token halving),
        # never values: no device sync.  install() is idempotent and
        # process-global.
        compile_telemetry.install()
        self._cost_model = cost_model_lib.EngineCostModel.from_engine_state(
            self.model.cfg, jax.tree_util.tree_leaves(self.params),
            jax.tree_util.tree_leaves(self._cache),
            n_chips=self._mesh.size if self._mesh is not None else 1,
            kv_dtype=config.kv_dtype if self._paged else None)

    @property
    def healthy(self) -> bool:
        return self.error is None

    @property
    def perf_cost_model(self) -> Optional[cost_model_lib.EngineCostModel]:
        """The static per-dispatch cost model behind the live gauges."""
        return self._cost_model

    def perf_snapshot(self) -> Optional[dict]:
        """Last perf-gauge sample the loop thread computed (mfu,
        hbm_bytes_per_token, arith_intensity, tokens_per_s,
        mean_context, mean_occupancy) — None until the first non-idle
        attribution window closes."""
        return dict(self._perf_last) if self._perf_last else None

    def perf_reset_window(self) -> None:
        """Restart the attribution window so the next sample covers
        only what follows (benchmarks bracket their measured region
        with this).  The start is stamped HERE, not lazily at the next
        loop sample: step()'s sample point sits after _admit_free, so a
        lazy stamp would exclude the first admission's prefill dispatch
        from the window while any wall-clock bracket around the region
        includes it — a systematic rate skew on short regions."""
        self._perf_window = (time.perf_counter(), self._perf_tokens,
                             self._perf_ctx_sum, self._perf_occ_sum)

    def arm_recompile_sentinel(self) -> None:
        """Declare warmup complete: every XLA compile from here on
        records a perf.recompile flight-recorder event, and
        SKYTPU_STRICT_RECOMPILE=1 escalates it to a hard failure in the
        compiling call.  prewarm() arms automatically on the paths that
        actually compile the shape set; lazy-compile callers (CPU
        tests) opt in here once their shapes are warm."""
        compile_telemetry.arm()

    @staticmethod
    def _validate_paging(config: EngineConfig, max_len: int) -> None:
        """Reject paging geometry that cannot work, naming the
        offending values: kv_page_size must divide every prefill bucket
        and max_seq_len (page-aligned inserts and prefix matches depend
        on it), and the pool must fit at least one max-length request
        plus the trash page.  kv_dtype and speculation are validated
        here too: both are properties of the paged substrate."""
        ps = config.kv_page_size
        if config.kv_dtype not in ('bf16', 'int8'):
            raise ValueError(
                f"kv_dtype must be 'bf16' or 'int8', got "
                f"{config.kv_dtype!r}")
        if config.kv_dtype == 'int8' and ps is None:
            raise ValueError(
                'kv_dtype=int8 quantizes the PAGED pool at scatter '
                'time; set kv_page_size (the contiguous cache keeps '
                'the model dtype)')
        if config.speculation < 0:
            raise ValueError(
                f'speculation must be a non-negative draft length, '
                f'got {config.speculation}')
        if config.speculation > 0:
            if ps is None:
                raise ValueError(
                    'speculation requires kv_page_size: rejected draft '
                    'rows must land in slot-owned/trash pages, not the '
                    'contiguous cache')
            if config.temperature != 0.0:
                raise ValueError(
                    f'speculation is greedy-only (accept = exact argmax '
                    f'match, lossless at temperature 0.0); got '
                    f'temperature={config.temperature}')
        if ps is None:
            return
        if ps <= 0:
            raise ValueError(
                f'kv_page_size must be a positive token count, got {ps}')
        offending = [b for b in config.prefill_buckets if b % ps != 0]
        if max_len % ps != 0:
            offending.append(max_len)
        if offending:
            raise ValueError(
                f'kv_page_size={ps} must divide every prefill bucket '
                f'and max_seq_len; offending values: '
                f'{sorted(set(offending))} (buckets='
                f'{config.prefill_buckets}, max_seq_len={max_len})')
        if config.kv_pages is not None:
            need = max_len // ps + 1
            if config.kv_pages < need:
                raise ValueError(
                    f'kv_pages={config.kv_pages} cannot hold one '
                    f'max-length request: need >= {need} '
                    f'(max_seq_len {max_len} / kv_page_size {ps} '
                    f'+ 1 trash page)')

    # ----- mesh setup --------------------------------------------------------
    def _setup_mesh(self):
        """Commit engine state to fixed NamedShardings.

        Params shard per the model's logical axes (serving_shardings),
        the KV cache over its kv-heads dim, and everything the host
        syncs (last tokens / lengths / decode output) is replicated.
        Committing at init means every later dispatch hits the same
        compiled programs — sharding never recompiles mid-traffic.
        """
        import flax.linen as nn
        from jax.sharding import NamedSharding, PartitionSpec as P

        from skypilot_tpu.inference.weights import serving_shardings
        from skypilot_tpu.parallel import mesh as mesh_lib

        mesh, axis = self._mesh, self.cfg.tensor_axis
        mcfg = self.model.cfg
        mesh_lib.validate_tensor_parallel(
            int(mesh.shape.get(axis, 1)), n_heads=mcfg.n_heads,
            n_kv_heads=getattr(mcfg, 'n_kv_heads', None))
        if getattr(self.model, 'mesh', None) is None:
            # The model needs the mesh too (activation constraints, the
            # one-hot embed that keeps a vocab-sharded table gather-free).
            self.model = self.model.clone(mesh=mesh)
        self._repl = NamedSharding(mesh, P())
        self._param_shardings = serving_shardings(self.model, mesh)
        # Unbox first: flax logical-axis metadata boxes carry init-time
        # sharding hints the engine has now consumed; apply() is
        # box-agnostic and device_put needs tree alignment with the
        # (unboxed) sharding tree.
        self.params = jax.device_put(nn.meta.unbox(self.params),
                                     self._param_shardings)
        # Per-layer KV cache [n_slots, n_kv_heads, max_len, head_dim]:
        # shard over kv heads (validated divisible above).  Computed from
        # an abstract cache trace so MoE/model variants with extra cache
        # leaves or head layouts still map correctly.
        kv = NamedSharding(mesh, P(None, axis))

        def _kv_or_repl(leaf):
            n_kv = leaf.shape[1] if len(leaf.shape) > 1 else 0
            tp = int(mesh.shape.get(axis, 1))
            return kv if n_kv and n_kv % tp == 0 else self._repl

        cache_abs = jax.eval_shape(self._make_cache, self.params)
        if self._paged:
            # The page pool [n_pages, n_kv_heads, page_size, head_dim]
            # shards over the same kv-heads dim as the dense cache, so
            # page gathers/scatters (dim 0) stay local per chip.  An
            # int8 pool's scale leaf [n_pages, H, page_size] shards
            # over the same H dim (axis 1 — _kv_or_repl is rank-
            # agnostic).
            cache_abs = jax.tree.map(self._pool_abs, cache_abs)
        self._cache_shardings = jax.tree.map(_kv_or_repl, cache_abs)
        # The chunked-prefill scratch cache [1, n_kv_heads, max_len, D]
        # shards over kv heads exactly like the big cache.
        scratch_abs = jax.eval_shape(lambda p: self._make_cache(p, 1),
                                     self.params)
        self._scratch_shardings = jax.tree.map(_kv_or_repl, scratch_abs)

    def _pool_shape(self, dense_shape) -> tuple:
        """Dense cache leaf [n, H, max_len, D] -> page-pool leaf
        [n_pages, H, page_size, D]."""
        return (self._pool_alloc.n_pages, dense_shape[1],
                self._page_size, dense_shape[3])

    def _pool_abs(self, dense_leaf):
        """Abstract pool node for one dense cache leaf: a plain
        ShapeDtypeStruct, or a QuantPages of (int8 data, f32 scales)
        under kv_dtype=int8."""
        shape = self._pool_shape(dense_leaf.shape)
        if self._kv_quant:
            return kv_quant.QuantPages(
                jax.ShapeDtypeStruct(shape, jnp.int8),
                jax.ShapeDtypeStruct(shape[:3], jnp.float32))
        return jax.ShapeDtypeStruct(shape, dense_leaf.dtype)

    def _make_cache(self, params, n: Optional[int] = None):
        """Trace a dummy decode batch; returns the per-layer cache for
        `n` slots (default: the engine's big cache; n=1: the chunked-
        prefill scratch)."""
        n = self.cfg.n_slots if n is None else n
        tokens = jnp.zeros((n, 1), jnp.int32)
        positions = jnp.zeros((n, 1), jnp.int32)
        _, cache = self.model.apply(
            {'params': params}, tokens, positions=positions,
            decode=True, mutable=['cache'])
        return cache['cache']

    # ----- jitted compute ----------------------------------------------------
    def _build_fns(self):
        model, temp = self.model, self.cfg.temperature

        def sample(logits, rng):                     # logits [..., V] f32
            if temp > 0.0:
                return jax.random.categorical(rng, logits / temp, axis=-1)
            return jnp.argmax(logits, axis=-1)

        def prefill_insert(params, big_cache, last_toks, lens, tokens,
                           lengths, slots, valid, rng):
            """Fused BATCHED prefill + slot insert: N prompts of one
            bucket in ONE dispatch, nothing synced.  tokens [N, P],
            lengths [N], slots [N], valid [N].  N is padded to a power
            of two by replicating row 0 (`valid`=0 for padding rows);
            batching the prefill keeps the MXU on one big [N*P] matmul
            instead of N small ones — the TTFT lever under admission
            bursts."""
            n, p = tokens.shape
            positions = jnp.broadcast_to(jnp.arange(p)[None, :], (n, p))
            logits, cache = model.apply(
                {'params': params}, tokens, positions=positions,
                decode=True, mutable=['cache'])
            last = jnp.take_along_axis(
                logits, (lengths - 1)[:, None, None], axis=1)[:, 0]  # [N,V]
            firsts = sample(last, rng)                               # [N]
            # Padding rows replicate row 0, so their duplicate scatter
            # writes must carry row 0's VALUE too — under temperature
            # sampling each row draws independently, and XLA leaves
            # which duplicate-index write wins unspecified.
            firsts = jnp.where(valid.astype(bool), firsts, firsts[0])

            def _ins(big, small):
                # small [N, H, max_len, D] rows (the model's prefill
                # cache is already full-length) scattered into big
                # [n_slots, H, max_len, D] at each row's slot index.
                return big.at[slots].set(small)

            big_cache = jax.tree_util.tree_map(_ins, big_cache,
                                               cache['cache'])
            return (big_cache, last_toks.at[slots].set(firsts),
                    lens.at[slots].set(lengths))

        steps = self.cfg.steps_per_call
        max_len = model.cfg.max_seq_len

        def decode(params, cache, last_tokens, lengths, rng):
            """`steps` tokens for every slot in one dispatch.  Returns
            out [steps+1, n_slots] (row 0 = the incoming last tokens, so
            freshly admitted slots' first tokens ride the same fetch)."""
            def body(carry, rng_t):
                cache, last, lens = carry
                # Clamp writes for slots running past the cap: confined
                # to slots being retired (their cache is re-inserted).
                positions = jnp.minimum(lens, max_len - 1)[:, None]
                logits, new_cache = model.apply(
                    {'params': params, 'cache': cache},
                    last[:, None], positions=positions,
                    decode=True, mutable=['cache'])
                nxt = sample(logits[:, 0, :], rng_t)         # [B]
                return (new_cache['cache'], nxt, lens + 1), nxt

            (cache, last, lens), toks = jax.lax.scan(
                body, (cache, last_tokens, lengths),
                jax.random.split(rng, steps))
            out = jnp.concatenate([last_tokens[None, :], toks], axis=0)
            return out, cache, last, lens                    # [T+1, B]

        def prefill_chunk(params, scratch, tokens, offset):
            """One INTERMEDIATE chunk of a long prompt: tokens [1, C]
            (all valid) land in the scratch cache at absolute positions
            offset..offset+C and attend over everything before them.
            Logits are never read, so XLA drops the lm-head matmul."""
            c = tokens.shape[1]
            positions = offset + jnp.arange(c)[None, :]
            _, cache = model.apply(
                {'params': params, 'cache': scratch}, tokens,
                positions=positions, decode=True, mutable=['cache'])
            return cache['cache']

        def prefill_chunk_insert(params, big_cache, last_toks, lens,
                                 scratch, tokens, length, offset,
                                 total_len, slot, rng):
            """FINAL chunk + slot insert in one dispatch: run the
            bucket-padded last chunk (`length` valid rows) against the
            scratch cache, sample the prompt's first token from its
            last valid position, and scatter the accumulated scratch
            into `slot` of the big cache.  Padding rows write garbage
            at positions >= total_len — masked (k_pos > q_pos) until
            the decode scatter overwrites them, the same invariant the
            fused bucket path relies on."""
            c = tokens.shape[1]
            positions = offset + jnp.arange(c)[None, :]
            logits, cache = model.apply(
                {'params': params, 'cache': scratch}, tokens,
                positions=positions, decode=True, mutable=['cache'])
            last = jax.lax.dynamic_index_in_dim(logits, length - 1,
                                                axis=1, keepdims=False)
            first = sample(last, rng)                        # [1]

            def _ins(big, small):
                return big.at[slot].set(small[0])

            big_cache = jax.tree_util.tree_map(_ins, big_cache,
                                               cache['cache'])
            return (big_cache, last_toks.at[slot].set(first[0]),
                    lens.at[slot].set(total_len))

        # ----- paged variants ------------------------------------------------
        # Prefill and chunked prefill still run against DENSE per-
        # request caches (identical programs, identical numerics); only
        # the insert tail changes — full pages scatter into the pool at
        # the page table's physical ids — and the decode step gathers
        # through the table inside the model (models/llama.py
        # _paged_attend).  Page tables are host-built arrays shipped
        # async; nothing below adds a sync.
        ps_ = self.cfg.kv_page_size
        n_pp = self._pages_per_slot
        # kv_dtype=int8: pool leaves are kv_quant.QuantPages pairs.
        # tree_maps that pair the pool against a DENSE cache treat the
        # QuantPages node as one leaf (is_leaf below); maps that pair
        # pool against pool (adopt) descend into raw arrays unchanged.
        _is_qp = lambda x: isinstance(x, kv_quant.QuantPages)  # noqa: E731

        def _to_pages(small):
            """Dense rows [N, H, L, D] -> page stacks [N, P, ps, H, D]
            -> [N, P, H, ps, D] matching pool scatter trailing dims."""
            n, h, length, d = small.shape
            pages = small.transpose(0, 2, 1, 3).reshape(
                n, n_pp, ps_, h, d)
            return pages.transpose(0, 1, 3, 2, 4)

        def prefill_insert_paged(params, pool, last_toks, lens, tokens,
                                 lengths, slots, pt_rows, valid, rng):
            """Fused batched prefill + PAGED insert: identical prefill
            compute, then every row's full-length dense cache scatters
            into the pool at its page-table row.  Entries past a row's
            reservation point at the trash page (garbage there is never
            at an unmasked position); padding rows replicate row 0's
            table, so their duplicate writes carry identical values."""
            n, p = tokens.shape
            positions = jnp.broadcast_to(jnp.arange(p)[None, :], (n, p))
            logits, cache = model.apply(
                {'params': params}, tokens, positions=positions,
                decode=True, mutable=['cache'])
            last = jnp.take_along_axis(
                logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
            firsts = sample(last, rng)
            firsts = jnp.where(valid.astype(bool), firsts, firsts[0])

            def _ins(pool_leaf, small):
                pages = _to_pages(small)
                if _is_qp(pool_leaf):
                    qd, s = kv_quant.quantize_kv(pages)
                    return kv_quant.QuantPages(
                        pool_leaf.data.at[pt_rows].set(qd),
                        pool_leaf.scale.at[pt_rows].set(s))
                return pool_leaf.at[pt_rows].set(pages)

            pool = jax.tree_util.tree_map(_ins, pool, cache['cache'],
                                          is_leaf=_is_qp)
            return (pool, last_toks.at[slots].set(firsts),
                    lens.at[slots].set(lengths))

        def decode_paged(params, pool, pt, last_tokens, lengths, rng):
            """`steps` tokens for every slot against the page pool —
            the model gathers/scatters through the (call-constant) page
            table; host contract identical to the dense decode."""
            def body(carry, rng_t):
                pool, last, lens = carry
                positions = jnp.minimum(lens, max_len - 1)[:, None]
                logits, new_cache = model.apply(
                    {'params': params, 'cache': pool},
                    last[:, None], positions=positions,
                    decode=True, page_table=pt, mutable=['cache'])
                nxt = sample(logits[:, 0, :], rng_t)
                return (new_cache['cache'], nxt, lens + 1), nxt

            (pool, last, lens), toks = jax.lax.scan(
                body, (pool, last_tokens, lengths),
                jax.random.split(rng, steps))
            out = jnp.concatenate([last_tokens[None, :], toks], axis=0)
            return out, pool, last, lens

        def verify_paged(params, pool, pt, last_tokens, lengths, drafts):
            """Speculative VERIFY: score every slot's k host-drafted
            tokens in ONE fixed-shape dispatch and accept the longest
            greedy-matching prefix.  [last, d_1..d_k] runs through the
            model's S = k+1 position-scatter path (the chunked-prefill
            machinery), so g[:, j] is the greedy continuation after
            consuming the draft prefix up to j; draft d_j is accepted
            iff d_j == g[:, j-1] and acceptance stops at the first
            mismatch.  m in [1, k+1] tokens commit per slot per call
            (m = 1 == plain decode: g[:, 0] IS the token decode would
            have sampled — greedy speculation is lossless).  Rejected
            rows leave K/V garbage strictly at positions >= the new
            length; the next call's writes land at exactly those
            positions before its gather, so the causal-mask invariant
            holds.  Empty slots draft zeros against trash-page tables;
            their m is garbage the host never reads.

            Output rows: [0] = incoming last tokens (same contract as
            decode), [1..k+1] = greedy continuations, [k+2] = m — the
            acceptance counts ride the SAME single fetch as the
            tokens, keeping the one-sync-per-step contract."""
            kk = drafts.shape[1]
            toks = jnp.concatenate([last_tokens[:, None], drafts],
                                   axis=1)                    # [B, k+1]
            positions = jnp.minimum(
                lengths[:, None] + jnp.arange(kk + 1)[None, :],
                max_len - 1)
            logits, new_cache = model.apply(
                {'params': params, 'cache': pool}, toks,
                positions=positions, decode=True, page_table=pt,
                mutable=['cache'])
            g = jnp.argmax(logits, axis=-1).astype(
                last_tokens.dtype)                            # [B, k+1]
            match = jnp.cumprod(
                (drafts == g[:, :kk]).astype(jnp.int32), axis=1)
            m = 1 + jnp.sum(match, axis=1)                    # [B]
            last = jnp.take_along_axis(g, (m - 1)[:, None],
                                       axis=1)[:, 0]
            out = jnp.concatenate(
                [last_tokens[None, :], g.T,
                 m[None, :].astype(last_tokens.dtype)], axis=0)
            return out, new_cache['cache'], last, lengths + m

        def gather_prefix(pool, pt_row):
            """Prefix-cache hit: materialize the matched pages into a
            DENSE scratch cache [1, H, max_len, D] so the remaining
            prompt rides the ordinary chunked-prefill path (S > 1
            against an existing cache) from offset = matched length.
            Unmatched entries are trash pages — garbage strictly above
            every query position the suffix will use."""
            def _g(leaf):
                if _is_qp(leaf):
                    g = kv_quant.dequantize_kv(
                        leaf.data[pt_row], leaf.scale[pt_row],
                        model.cfg.dtype)          # [P, H, ps, D]
                else:
                    g = leaf[pt_row]              # [P, H, ps, D]
                g = g.transpose(1, 0, 2, 3)       # [H, P, ps, D]
                return g.reshape(1, g.shape[0], n_pp * ps_, g.shape[3])

            return jax.tree_util.tree_map(_g, pool, is_leaf=_is_qp)

        def chunk_insert_paged(params, pool, last_toks, lens, scratch,
                               tokens, length, offset, total_len, slot,
                               pt_row, rng):
            """Final chunk + PAGED slot insert: the dense chunk body,
            then the accumulated scratch scatters into the pool at this
            request's page-table row.  Shared prefix pages receive
            value-identical write-backs (the scratch was gathered from
            them and chunk writes land past the match), so concurrent
            sharers never observe a change."""
            c = tokens.shape[1]
            positions = offset + jnp.arange(c)[None, :]
            logits, cache = model.apply(
                {'params': params, 'cache': scratch}, tokens,
                positions=positions, decode=True, mutable=['cache'])
            last = jax.lax.dynamic_index_in_dim(logits, length - 1,
                                                axis=1, keepdims=False)
            first = sample(last, rng)

            def _ins(pool_leaf, small):
                pages = _to_pages(small)[0]
                if _is_qp(pool_leaf):
                    qd, s = kv_quant.quantize_kv(pages)
                    return kv_quant.QuantPages(
                        pool_leaf.data.at[pt_row].set(qd),
                        pool_leaf.scale.at[pt_row].set(s))
                return pool_leaf.at[pt_row].set(pages)

            pool = jax.tree_util.tree_map(_ins, pool, cache['cache'],
                                          is_leaf=_is_qp)
            return (pool, last_toks.at[slot].set(first[0]),
                    lens.at[slot].set(total_len))

        def export_pages(pool, pt_row):
            """Disaggregation export: gather one slot's pages OFF the
            pool as page stacks [P, H, ps, D] per leaf (P =
            pages_per_slot; entries past the reservation gather the
            trash page and are sliced away at serialization).  The
            pool is read-only here — never donated — so the live cache
            survives the export."""
            return jax.tree_util.tree_map(lambda leaf: leaf[pt_row],
                                          pool)

        def adopt_insert(pool, last_toks, lens, data, scatter_row, slot,
                         first, length):
            """Disaggregation adopt: scatter a KV handoff's page
            stacks into the pool at this request's freshly allocated
            pages (scatter_row entries past the transferred pages
            target the trash page, so the zero-padded stack rows land
            somewhere harmless), and seed the slot's last token /
            length so the next decode call continues the transferred
            request exactly where the prefill replica's sampling left
            it — no per-token recompute."""
            def _ins(pool_leaf, data_leaf):
                return pool_leaf.at[scatter_row].set(data_leaf)

            pool = jax.tree_util.tree_map(_ins, pool, data)
            return (pool, last_toks.at[slot].set(first),
                    lens.at[slot].set(length))

        if self._paged:
            prefill_insert = prefill_insert_paged
            decode = decode_paged
            prefill_chunk_insert = chunk_insert_paged
            self._gather_raw = gather_prefix
            self._export_raw = export_pages
            self._adopt_raw = adopt_insert
            self._verify_raw = verify_paged
        self._prefill_raw = prefill_insert
        self._decode_raw = decode
        self._chunk_raw = prefill_chunk
        self._chunk_insert_raw = prefill_chunk_insert
        if self._paged:
            self._build_paged_jits()
        elif self._mesh is None:
            self._prefill_insert = jax.jit(prefill_insert,
                                           donate_argnums=(1, 2, 3))
            self._decode = jax.jit(decode, donate_argnums=(1, 2, 3))
            self._prefill_chunk = jax.jit(prefill_chunk,
                                          donate_argnums=(1,))
            # No scratch donation here: a [1, ...] scratch leaf can
            # never alias the [n_slots, ...] outputs, and an unusable
            # donation only buys a warning.
            self._chunk_insert = jax.jit(prefill_chunk_insert,
                                         donate_argnums=(1, 2, 3))
        else:
            # Pin every program to the engine's committed shardings:
            # donated state (cache/last/lens) comes back in the same
            # placement it went in, so call k+1 reuses call k's cache
            # entry — the zero-recompile invariant, now sharded.  The
            # host-fetched output and all host-built inputs (tokens,
            # lengths, slots, rng) are replicated.
            p_sh, c_sh, r = (self._param_shardings, self._cache_shardings,
                             self._repl)
            self._prefill_insert = jax.jit(
                prefill_insert, donate_argnums=(1, 2, 3),
                in_shardings=(p_sh, c_sh, r, r, r, r, r, r, r),
                out_shardings=(c_sh, r, r))
            self._decode = jax.jit(
                decode, donate_argnums=(1, 2, 3),
                in_shardings=(p_sh, c_sh, r, r, r),
                out_shardings=(r, c_sh, r, r))
            s_sh = self._scratch_shardings
            self._prefill_chunk = jax.jit(
                prefill_chunk, donate_argnums=(1,),
                in_shardings=(p_sh, s_sh, r, r), out_shardings=s_sh)
            self._chunk_insert = jax.jit(
                prefill_chunk_insert, donate_argnums=(1, 2, 3),
                in_shardings=(p_sh, c_sh, r, r, s_sh, r, r, r, r, r, r),
                out_shardings=(c_sh, r, r))

    def _build_paged_jits(self):
        """Jit wiring for the paged programs (the paged twin of the
        branches in _build_fns): same donation discipline — the pool
        rides through every program donated, so call k+1 reuses call
        k's buffer — with the page table and gather output never
        donated (the table is reused across calls; the pool outlives a
        prefix gather)."""
        if self._mesh is None:
            self._prefill_insert = jax.jit(self._prefill_raw,
                                           donate_argnums=(1, 2, 3))
            self._decode = jax.jit(self._decode_raw,
                                   donate_argnums=(1, 3, 4))
            if self._spec_k:
                self._verify = jax.jit(self._verify_raw,
                                       donate_argnums=(1, 3, 4))
            self._prefill_chunk = jax.jit(self._chunk_raw,
                                          donate_argnums=(1,))
            self._chunk_insert = jax.jit(self._chunk_insert_raw,
                                         donate_argnums=(1, 2, 3))
            # skytpu: allow-recompile(one fixed shape per engine; the pool is read-only here — donating it would free the live cache — and the page-table row is a tiny per-call upload)
            self._gather_prefix = jax.jit(self._gather_raw)
            self._adopt_insert = jax.jit(self._adopt_raw,
                                         donate_argnums=(0, 1, 2))
            # skytpu: allow-recompile(one fixed shape per engine; the export gather reads the live pool — donating it would free the cache under the in-flight decode)
            self._export_pages = jax.jit(self._export_raw)
            return
        p_sh, c_sh, r = (self._param_shardings, self._cache_shardings,
                         self._repl)
        s_sh = self._scratch_shardings
        self._prefill_insert = jax.jit(
            self._prefill_raw, donate_argnums=(1, 2, 3),
            in_shardings=(p_sh, c_sh, r, r, r, r, r, r, r, r),
            out_shardings=(c_sh, r, r))
        self._decode = jax.jit(
            self._decode_raw, donate_argnums=(1, 3, 4),
            in_shardings=(p_sh, c_sh, r, r, r, r),
            out_shardings=(r, c_sh, r, r))
        if self._spec_k:
            self._verify = jax.jit(
                self._verify_raw, donate_argnums=(1, 3, 4),
                in_shardings=(p_sh, c_sh, r, r, r, r),
                out_shardings=(r, c_sh, r, r))
        self._prefill_chunk = jax.jit(
            self._chunk_raw, donate_argnums=(1,),
            in_shardings=(p_sh, s_sh, r, r), out_shardings=s_sh)
        self._chunk_insert = jax.jit(
            self._chunk_insert_raw, donate_argnums=(1, 2, 3),
            in_shardings=(p_sh, c_sh, r, r, s_sh, r, r, r, r, r, r, r),
            out_shardings=(c_sh, r, r))
        self._gather_prefix = jax.jit(
            self._gather_raw, in_shardings=(c_sh, r), out_shardings=s_sh)
        # Handoff programs: adopt data / export stacks are replicated
        # (they cross the host boundary as numpy either way); the pool
        # keeps its committed sharding through both.
        d_sh = jax.tree.map(lambda _: r, c_sh)
        self._adopt_insert = jax.jit(
            self._adopt_raw, donate_argnums=(0, 1, 2),
            in_shardings=(c_sh, r, r, d_sh, r, r, r, r),
            out_shardings=(c_sh, r, r))
        self._export_pages = jax.jit(
            self._export_raw, in_shardings=(c_sh, r), out_shardings=d_sh)

    def _init_cache(self):
        """Materialize the big cache by tracing a dummy decode batch.
        Under a mesh it is created ALREADY sharded (jit out_shardings) —
        at no point does a full cache exist on one device."""
        n = self.cfg.n_slots
        if self._paged:
            self._init_pool()
            return
        if self._mesh is None:
            self._cache = self._make_cache(self.params)
            self._last_d = jnp.zeros((n,), jnp.int32)
            self._lens_d = jnp.zeros((n,), jnp.int32)
            return
        self._cache = jax.jit(
            self._make_cache,
            out_shardings=self._cache_shardings)(self.params)
        self._last_d = jax.device_put(jnp.zeros((n,), jnp.int32),
                                      self._repl)
        self._lens_d = jax.device_put(jnp.zeros((n,), jnp.int32),
                                      self._repl)

    def _init_pool(self):
        """Materialize the PAGE POOL: the dense cache tree's shape with
        [n_slots, ..., max_seq_len, ...] swapped for [n_pages, ...,
        page_size, ...].  Total HBM = n_pages x page bytes — sized by
        kv_pages, not by n_slots x max_seq_len; that delta is the
        reservation paging removes.  Created sharded under a mesh."""
        n = self.cfg.n_slots
        cache_abs = jax.eval_shape(self._make_cache, self.params)

        def make_pool(_params):
            # _pool_abs: a ShapeDtypeStruct, or a QuantPages pair of
            # them under int8 — zero both through the pytree.
            return jax.tree.map(
                lambda l: jax.tree.map(
                    lambda a: jnp.zeros(a.shape, a.dtype),
                    self._pool_abs(l)),
                cache_abs)

        if self._mesh is None:
            self._cache = make_pool(self.params)
            self._last_d = jnp.zeros((n,), jnp.int32)
            self._lens_d = jnp.zeros((n,), jnp.int32)
            return
        self._cache = jax.jit(
            make_pool, out_shardings=self._cache_shardings)(self.params)
        self._last_d = jax.device_put(jnp.zeros((n,), jnp.int32),
                                      self._repl)
        self._lens_d = jax.device_put(jnp.zeros((n,), jnp.int32),
                                      self._repl)

    def _optimize_layouts(self):
        """TPU: pre-lay-out the weights the way the decode loop wants.

        For 3D projection kernels (e.g. [embed, heads, head_dim]) the
        decode matvecs prefer a different tiled layout than the default;
        left alone, XLA materializes a relaid-out copy of EVERY weight
        as an HLO temp of the decode program — ~3 GB extra HBM for a 7B,
        the difference between fitting one v5e chip and OOM.  Fix: AOT-
        compile the decode step with AUTO input layouts, then device_put
        params (and the cache/engine state, which must match since they
        are donated through the same executable) into the layouts the
        compiler chose.  Prefill executables are then pinned to those
        same layouts per bucket in _admit_group.
        """
        from jax.experimental.layout import Format, Layout

        _abs = self._abs_tree
        auto = jax.tree.map(lambda _: Format(Layout.AUTO), self.params)
        rng_abs = jax.ShapeDtypeStruct(self._rng.shape, self._rng.dtype)
        compiled = jax.jit(
            self._decode_raw, donate_argnums=(1, 2, 3),
            in_shardings=(auto, Format(Layout.AUTO), Format(Layout.AUTO),
                          Format(Layout.AUTO), Format(Layout.AUTO)),
            # Donated inputs require matching AUTO outputs (out row 0 is
            # host-fetched; its layout is immaterial).
            out_shardings=(Format(Layout.AUTO), Format(Layout.AUTO),
                           Format(Layout.AUTO), Format(Layout.AUTO)),
        ).lower(_abs(self.params), _abs(self._cache), _abs(self._last_d),
                _abs(self._lens_d), rng_abs).compile()
        fmts, _ = compiled.input_formats
        self._fmt_params, self._fmt_cache = fmts[0], fmts[1]
        self._fmt_last, self._fmt_lens = fmts[2], fmts[3]
        # donate=True: relayout leaf-by-leaf in place — without it the
        # whole param tree exists twice mid-put (2x 13.3 GB for a 7B).
        self.params = jax.device_put(self.params, self._fmt_params,
                                     donate=True)
        self._cache = jax.device_put(self._cache, self._fmt_cache,
                                     donate=True)
        self._last_d = jax.device_put(self._last_d, self._fmt_last,
                                      donate=True)
        self._lens_d = jax.device_put(self._lens_d, self._fmt_lens,
                                      donate=True)
        self._decode = compiled
        self._params_owned = True    # relaid-out tree is engine-private

    def _prefill_for(self, bucket: int, padded_n: int):
        """Prefill executable for one (bucket, batch) shape, pinned to
        the decode-chosen param/cache layouts on TPU (plain jit
        elsewhere)."""
        if self._fmt_params is None:
            return self._prefill_insert
        key = (bucket, padded_n)
        fn = self._prefill_compiled.get(key)
        if fn is None:
            _abs = self._abs_tree
            toks = jax.ShapeDtypeStruct((padded_n, bucket), jnp.int32)
            vec = jax.ShapeDtypeStruct((padded_n,), jnp.int32)
            rng_abs = jax.ShapeDtypeStruct(self._rng.shape, self._rng.dtype)
            fn = jax.jit(
                self._prefill_raw, donate_argnums=(1, 2, 3),
                in_shardings=(self._fmt_params, self._fmt_cache,
                              self._fmt_last, self._fmt_lens,
                              None, None, None, None, None),
                # Outputs feed the next decode call via donation — they
                # must come back in the decode-chosen layouts.
                out_shardings=(self._fmt_cache, self._fmt_last,
                               self._fmt_lens),
            ).lower(_abs(self.params), _abs(self._cache),
                    _abs(self._last_d), _abs(self._lens_d), toks, vec, vec,
                    vec, rng_abs).compile()
            self._prefill_compiled[key] = fn
        return fn

    # ----- chunked prefill executables ---------------------------------------
    def _abs_tree(self, tree):
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), tree)

    def _new_scratch(self):
        """Fresh zeroed single-request scratch cache, in the engine's
        committed shardings under a mesh (default layouts otherwise —
        the chunk programs keep it there end to end)."""
        if self._scratch_fn is None:
            make = lambda p: self._make_cache(p, 1)  # noqa: E731
            if self._scratch_shardings is not None:
                self._scratch_fn = jax.jit(
                    make, out_shardings=self._scratch_shardings)
            else:
                # skytpu: allow-recompile(compiles once per engine; a creation fn has no donatable input and the scratch rides default layouts end to end)
                self._scratch_fn = jax.jit(make)
        return self._scratch_fn(self.params)

    def _chunk_for(self, width: int):
        """Intermediate-chunk executable for one chunk width, pinned to
        the decode-chosen param layouts on TPU (plain jit elsewhere —
        the scratch cache always rides default layouts)."""
        if self._fmt_params is None:
            return self._prefill_chunk
        key = ('chunk', width)
        fn = self._chunk_compiled.get(key)
        if fn is None:
            toks = jax.ShapeDtypeStruct((1, width), jnp.int32)
            scalar = jax.ShapeDtypeStruct((), jnp.int32)
            scratch_abs = jax.eval_shape(lambda p: self._make_cache(p, 1),
                                         self._abs_tree(self.params))
            fn = jax.jit(
                self._chunk_raw, donate_argnums=(1,),
                in_shardings=(self._fmt_params, None, None, None),
            ).lower(self._abs_tree(self.params), scratch_abs, toks,
                    scalar).compile()
            self._chunk_compiled[key] = fn
        return fn

    def _chunk_insert_for(self, bucket: int):
        """Final-chunk-plus-insert executable for one bucket width: the
        donated big cache / last / lens must come back in the layouts
        the decode executable was pinned to."""
        if self._fmt_params is None:
            return self._chunk_insert
        key = ('insert', bucket)
        fn = self._chunk_compiled.get(key)
        if fn is None:
            toks = jax.ShapeDtypeStruct((1, bucket), jnp.int32)
            scalar = jax.ShapeDtypeStruct((), jnp.int32)
            rng_abs = jax.ShapeDtypeStruct(self._rng.shape, self._rng.dtype)
            scratch_abs = jax.eval_shape(lambda p: self._make_cache(p, 1),
                                         self._abs_tree(self.params))
            fn = jax.jit(
                self._chunk_insert_raw, donate_argnums=(1, 2, 3),
                in_shardings=(self._fmt_params, self._fmt_cache,
                              self._fmt_last, self._fmt_lens,
                              None, None, None, None, None, None, None),
                out_shardings=(self._fmt_cache, self._fmt_last,
                               self._fmt_lens),
            ).lower(self._abs_tree(self.params), self._abs_tree(self._cache),
                    self._abs_tree(self._last_d),
                    self._abs_tree(self._lens_d), scratch_abs, toks,
                    scalar, scalar, scalar, scalar, rng_abs).compile()
            self._chunk_compiled[key] = fn
        return fn

    # ----- public API --------------------------------------------------------
    @property
    def max_prompt_len(self) -> int:
        """Longest admissible prompt: max_seq_len - 1 (one generated
        token must fit the cache), optionally capped by the
        EngineConfig.max_prompt_len knob."""
        limit = self.model.cfg.max_seq_len - 1
        if self.cfg.max_prompt_len is not None:
            limit = min(limit, self.cfg.max_prompt_len)
        return limit

    @property
    def queued_prefill_tokens(self) -> int:
        """Prompt tokens accepted but not yet prefilled — the same value
        the skytpu_engine_queued_prefill_tokens gauge exports.  Cheap
        (one int read, no device sync): the inference server stamps it
        on every response header so the serve LB's admission control
        sees the backlog without an extra round trip."""
        return max(0, self._queued_tokens)

    def submit(self, prompt_ids: List[int],
               max_new_tokens: int = 64,
               request_id: Optional[str] = None) -> Request:
        limit = self.max_prompt_len
        if len(prompt_ids) > limit:
            raise ValueError(
                f'prompt len {len(prompt_ids)} exceeds max_prompt_len '
                f'{limit} (model max_seq_len '
                f'{self.model.cfg.max_seq_len})')
        cache_len = self.model.cfg.max_seq_len
        if len(prompt_ids) + max_new_tokens > cache_len:
            max_new_tokens = cache_len - len(prompt_ids)
        req = Request(list(prompt_ids), max_new_tokens,
                      request_id=request_id)
        self._enqueue(req)
        return req

    def _enqueue(self, req: Request) -> None:
        """Publish one validated request to the loop thread.  Every
        flag the loop reads (export, adopt, no_prefix) must be set
        BEFORE this — the loop may admit and even finish the request
        the moment it lands in a queue."""
        with self._submit_lock:
            if self.error is not None:
                raise RuntimeError(
                    f'decode engine is dead: {self.error!r}')
            # Prompts beyond the largest bucket take the chunked path.
            if len(req.prompt_ids) > self.cfg.prefill_buckets[-1]:
                self._long_q.put(req)
            else:
                self._prefill_q.put(req)
            self._queued_tokens += len(req.prompt_ids)
        metrics_lib.inc_counter('skytpu_engine_requests_total')

    def generate(self, prompt_ids: List[int],
                 max_new_tokens: int = 64) -> List[int]:
        """Synchronous helper: submit and wait."""
        return self.submit(prompt_ids, max_new_tokens).tokens()

    # ----- disaggregated prefill/decode --------------------------------------
    def submit_prefill(self, prompt_ids: List[int],
                       max_new_tokens: int = 64,
                       request_id: Optional[str] = None) -> Request:
        """PREFILL-role admission: run the ordinary prefill machinery
        (fused bucket, chunked, prefix-cache hits — identical compiled
        programs), sample the first token, and HOLD the request's KV
        pages for export instead of decoding.  The request finishes
        after exactly one emitted token; `export_result` then yields
        the pages + token for kv_transfer serialization.  Page
        admission charges only ceil((prompt+1)/page) pages — the
        decode budget is the DECODE pool's to reserve — which is the
        packing win a dedicated prefill replica exists for.
        `max_new_tokens` is the downstream decode budget and only
        travels in the payload."""
        if not self._paged:
            raise RuntimeError(
                'disaggregated prefill requires the paged KV cache '
                '(kv_page_size): pages are the transfer unit')
        limit = self.max_prompt_len
        if len(prompt_ids) > limit:
            raise ValueError(
                f'prompt len {len(prompt_ids)} exceeds max_prompt_len '
                f'{limit} (model max_seq_len '
                f'{self.model.cfg.max_seq_len})')
        req = Request(list(prompt_ids), 1, request_id=request_id)
        req.export = True
        req.downstream_max_new = max_new_tokens
        self._enqueue(req)
        return req

    def export_result(self, req: Request) -> dict:
        """The finished prefill-role request's transferable state:
        {'first_token', 'prompt_len', 'n_kv_pages', 'leaves'} with
        leaves as HOST numpy page stacks [n_kv_pages, H, page_size, D]
        in cache-tree leaf order.  Call only after `req.tokens()`
        returned (the loop thread dispatched the export gather before
        finishing the request); the device->host sync happens HERE, on
        the caller's thread, never the engine loop's."""
        if req.kv_export is None:
            raise RuntimeError(
                'no export staged for this request (not submitted via '
                'submit_prefill, not finished, or the engine died '
                'mid-request)')
        staged = req.kv_export
        n_kv = staged['n_kv_pages']
        if staged['leaves'] is None:
            raise RuntimeError('export already consumed for this '
                               'request')
        leaves = [np.asarray(leaf)[:n_kv]
                  for leaf in jax.tree_util.tree_leaves(staged['leaves'])]
        # Drop the device-side gather now that the host copy exists:
        # it holds a full slot's worth of KV HBM ([pages_per_slot,...]
        # per leaf, whatever the prompt length), and the Request
        # object lives until the HTTP push completes — N concurrent
        # handoffs would otherwise pin N extra slots of HBM.
        staged['leaves'] = None
        return {'first_token': staged['first_token'],
                'prompt_len': staged['prompt_len'],
                'n_kv_pages': n_kv,
                'leaves': leaves}

    def submit_adopt(self, prompt_ids: List[int], first_token: int,
                     kv_leaves: List[np.ndarray],
                     max_new_tokens: int = 64,
                     request_id: Optional[str] = None,
                     page_size: Optional[int] = None) -> Request:
        """DECODE-role admission of a KV handoff: the prompt's pages
        were prefilled elsewhere; adopt them into this engine's pool
        and continue decoding from the already-sampled first token.
        The emitted stream (first token included, via the ordinary
        row-0 mechanics) is token-identical to serving the prompt
        monolithically.  `kv_leaves` are host numpy page stacks
        [n_kv_pages, H, page_size, D] in cache-tree leaf order."""
        if not self._paged:
            raise RuntimeError(
                'adopting a KV handoff requires the paged KV cache '
                '(kv_page_size): pages are the transfer unit')
        if page_size is not None and page_size != self._page_size:
            raise ValueError(
                f'kv handoff page size {page_size} != this engine\'s '
                f'{self._page_size} — prefill and decode pools must '
                f'agree on kv_page_size')
        if not kv_leaves:
            raise ValueError('kv handoff carries no cache leaves')
        n_kv = kv_leaves[0].shape[0]
        expect = -(-len(prompt_ids) // self._page_size)
        if n_kv != expect:
            raise ValueError(
                f'kv handoff page count {n_kv} does not cover the '
                f'{len(prompt_ids)}-token prompt (expected {expect} '
                f'pages of {self._page_size})')
        if n_kv > self._pages_per_slot:
            raise ValueError(
                f'kv handoff of {n_kv} pages exceeds this engine\'s '
                f'{self._pages_per_slot} pages per slot '
                f'(max_seq_len {self.model.cfg.max_seq_len})')
        # The payload must match this engine's cache tree exactly —
        # leaf count, per-page shape (heads, page_size, head_dim) and
        # dtype.  A model-config mismatch rejected HERE is a 422 to
        # the pusher; reaching the loop thread it would be an engine-
        # killing crash that strands every in-flight request.
        pool_leaves = jax.tree_util.tree_leaves(self._cache)
        if len(kv_leaves) != len(pool_leaves):
            raise ValueError(
                f'kv handoff carries {len(kv_leaves)} cache leaves; '
                f'this engine\'s cache tree has {len(pool_leaves)} '
                f'(model mismatch between prefill and decode pools)')
        for i, (leaf, pool_leaf) in enumerate(
                zip(kv_leaves, pool_leaves)):
            want_shape = tuple(pool_leaf.shape[1:])
            if tuple(leaf.shape[1:]) != want_shape or \
                    leaf.shape[0] != n_kv:
                raise ValueError(
                    f'kv handoff leaf {i} has page shape '
                    f'{tuple(leaf.shape)}; this engine expects '
                    f'[{n_kv}, {", ".join(map(str, want_shape))}] '
                    f'(model mismatch between prefill and decode '
                    f'pools)')
            if leaf.dtype != pool_leaf.dtype:
                raise ValueError(
                    f'kv handoff leaf {i} dtype {leaf.dtype} != this '
                    f'engine\'s {pool_leaf.dtype}')
        cache_len = self.model.cfg.max_seq_len
        if len(prompt_ids) + max_new_tokens > cache_len:
            max_new_tokens = cache_len - len(prompt_ids)
        if max_new_tokens < 1:
            raise ValueError(
                f'prompt of {len(prompt_ids)} tokens leaves no room '
                f'to decode (max_seq_len {cache_len})')
        req = Request(list(prompt_ids), max_new_tokens,
                      request_id=request_id)
        req.adopt = (int(first_token), kv_leaves)
        with self._submit_lock:
            if self.error is not None:
                raise RuntimeError(
                    f'decode engine is dead: {self.error!r}')
            self._adopt_q.put(req)
        metrics_lib.inc_counter('skytpu_engine_requests_total')
        return req

    def drain(self) -> None:
        """Run the pipelined loop until FULLY idle: queues empty, no
        active or chunk-prefilling request, nothing in flight (the last
        retire typically leaves one garbage call in flight — see
        step_pipelined)."""
        while (self._inflight is not None or
               not self._prefill_q.empty() or
               not self._long_q.empty() or
               not self._adopt_q.empty() or
               self._ready_q or self._hit_q or self._adopt_ready or
               self._chunked is not None or
               any(s is not None for s in self._slots)):
            self.step_pipelined()

    def _stage(self, params):
        """Place a new tree into the engine's committed layouts /
        shardings.  Returns (tree, owned): owned marks a device copy
        the engine is normally the only holder of, so dropping the
        engine's reference at retire time frees its HBM."""
        if self._fmt_params is not None:
            # TPU layout path: lay the new tree out into the formats
            # the decode executable was pinned to.
            return jax.device_put(params, self._fmt_params), True
        if self._param_shardings is not None:
            # Mesh path: land the new tree (host numpy from an RL
            # learner, or another placement) in the SAME committed
            # shardings — the compiled programs keep hitting cache.
            import flax.linen as nn
            return jax.device_put(nn.meta.unbox(params),
                                  self._param_shardings), True
        return params, False

    def update_params(self, params) -> None:
        """Swap the served weights WITHOUT draining (rolling weight
        refresh, the RL rollout/update alternation): double-buffered
        in-flight swap.  The new tree is STAGED into the engine's
        committed layouts/shardings here (the device_put overlaps with
        live serving), INSTALLED by the loop at its next dispatch
        boundary — so every individual dispatch sees exactly one tree
        and every compiled program stays hot — and the old buffers are
        RELEASED once the last call dispatched against them has
        retired.  Active slots and in-flight calls keep running; the
        first dispatch after the install (mid-request included — that
        is the rolling-refresh contract) samples from the new weights.

        Called with no loop thread running (manual step()/RL
        alternation), the caller IS the dispatcher, so the install
        happens before this returns."""
        staged = self._stage(params)
        with self._params_lock:
            # Re-staged before install: the never-served copy's only
            # reference drops here and it frees immediately.
            self._staged_params = staged
        if self._thread is None or not self._thread.is_alive():
            self._install_staged()

    def _install_staged(self) -> None:
        """Dispatch-boundary half of update_params: swap the staged
        tree in; the outgoing tree joins the retiring list until every
        call dispatched against it has retired."""
        with self._params_lock:
            staged, self._staged_params = self._staged_params, None
        if staged is None:
            return
        old, old_owned = self.params, self._params_owned
        self.params, self._params_owned = staged
        if old_owned:
            self._retiring_params.append(old)
        if self._inflight is None:
            self._release_retiring()

    def _release_retiring(self) -> None:
        """Drop the engine's references to swapped-out param trees.
        Called right after the pipelined sync — every call dispatched
        before the install has retired by then, so in the production
        case (the engine holds the only reference to its staged copy)
        the old tree's HBM frees here, bounding the double-buffer
        window to one loop iteration.  Reference-drop rather than
        explicit Array.delete(): device_put may ALIAS caller buffers
        (zero-copy when placement already matches), and deleting an
        aliased buffer would corrupt the caller's live tree — the
        runtime's refcount frees exactly when the last holder lets
        go."""
        if self._retiring_params:
            self._retiring_params = []

    def prewarm(self) -> None:
        """Compile every prefill shape up front (TPU layout path only).

        Admission pads groups to powers of two, so the shape set is
        |buckets| x (log2(n_slots)+1).  Without this, the first burst
        that hits a new shape stalls the whole decode batch behind a
        multi-second XLA compile — a mid-traffic TTFT/TPOT spike.

        Mesh path: the sharded executables live in the ordinary jit
        cache, so prewarming EXECUTES one dummy dispatch per admission
        shape plus one decode call (valid=0 rows into slot 0 — the
        engine is idle, nothing reads the scribbled state, and the next
        real admission overwrites it).  This matters most exactly here:
        a 70B-class sharded program is the longest compile in the
        system, and must not be paid under live traffic.
        """
        if self._mesh is not None:
            self._prewarm_mesh()
            compile_telemetry.arm()
            return
        if self._fmt_params is None:
            # Lazy-compile path (no TPU layout pass): nothing was
            # compiled here, so arming the recompile sentinel would
            # flag the first LEGITIMATE compiles.  Callers that warm
            # their shapes by running them opt in via
            # arm_recompile_sentinel().
            return
        # Include the first power of two >= n_slots: _admit_group pads to
        # the NEXT power of two, which exceeds n_slots when n_slots is not
        # itself one (n_slots=6, burst of 5 -> pad 8) — without it the
        # first such burst hits the mid-traffic compile stall prewarm
        # exists to prevent.
        for bucket in self.cfg.prefill_buckets:
            for size in self._prewarm_sizes():
                self._prefill_for(bucket, size)
        if self._chunking_possible():
            self._new_scratch()     # compiles the scratch-init program
            self._chunk_for(self.cfg.prefill_buckets[-1])
            for bucket in self.cfg.prefill_buckets:
                self._chunk_insert_for(bucket)
        # The full admissible shape set is compiled: any compile after
        # this point is a mid-traffic stall — arm the runtime sentinel
        # (the twin of the static recompile-hazard rule).
        compile_telemetry.arm()

    def _chunking_possible(self) -> bool:
        """True when an admissible prompt can exceed the largest bucket
        (so the chunked-prefill programs are reachable)."""
        return self.max_prompt_len > self.cfg.prefill_buckets[-1]

    def _prewarm_sizes(self):
        """Padded admission-group row counts: powers of two up to and
        including the first one >= n_slots (see prewarm)."""
        n, sizes = 1, []
        while True:
            sizes.append(n)
            if n >= self.cfg.n_slots:
                break
            n *= 2
        return sizes

    def _prewarm_mesh(self):
        """Compile every sharded shape by executing dummy dispatches.

        Must run before start() (single-threaded, engine idle).  All
        rows carry valid=0 and target slot 0; lengths=1 keeps the
        last-token gather in range.  Slot 0's cache/last/lens end up
        scribbled — harmless, an insert overwrites a slot wholesale and
        no slot is active to read them.
        """
        trash_row = (jnp.full((self._pages_per_slot,), TRASH_PAGE,
                              jnp.int32) if self._paged else None)
        for bucket in self.cfg.prefill_buckets:
            for size in self._prewarm_sizes():
                tokens = jnp.zeros((size, bucket), jnp.int32)
                ones = jnp.ones((size,), jnp.int32)
                zeros = jnp.zeros((size,), jnp.int32)
                if self._paged:
                    rows = jnp.broadcast_to(trash_row[None, :],
                                            (size, self._pages_per_slot))
                    (self._cache, self._last_d,
                     self._lens_d) = self._prefill_insert(
                         self.params, self._cache, self._last_d,
                         self._lens_d, tokens, ones, zeros, rows, zeros,
                         self._next_rng())
                else:
                    (self._cache, self._last_d,
                     self._lens_d) = self._prefill_insert(
                         self.params, self._cache, self._last_d,
                         self._lens_d, tokens, ones, zeros, zeros,
                         self._next_rng())
        if self._chunking_possible() or (self._paged and
                                         self._radix is not None):
            # Chunked-prefill shapes: one intermediate-chunk program
            # (largest bucket) + one final-insert program per bucket
            # (the prefix-cache hit path rides them even when no prompt
            # exceeds the largest bucket).  Dummy dispatches scribble
            # slot 0 / the trash page like the loop above.
            chunk = self.cfg.prefill_buckets[-1]
            one = jnp.ones((), jnp.int32)
            zero = jnp.zeros((), jnp.int32)
            for bucket in self.cfg.prefill_buckets:
                scratch = self._prefill_chunk(
                    self.params, self._new_scratch(),
                    jnp.zeros((1, chunk), jnp.int32), zero)
                if self._paged:
                    (self._cache, self._last_d,
                     self._lens_d) = self._chunk_insert(
                         self.params, self._cache, self._last_d,
                         self._lens_d, scratch,
                         jnp.zeros((1, bucket), jnp.int32), one, zero,
                         one, zero, trash_row, self._next_rng())
                else:
                    (self._cache, self._last_d,
                     self._lens_d) = self._chunk_insert(
                         self.params, self._cache, self._last_d,
                         self._lens_d, scratch,
                         jnp.zeros((1, bucket), jnp.int32), one, zero,
                         one, zero, self._next_rng())
        if self._paged and self._radix is not None:
            self._gather_prefix(self._cache, trash_row)
        if self._paged:
            # Handoff programs (disaggregated serving): one dummy
            # export gather plus one adopt scatter whose rows all land
            # in the trash page (slot 0's last/lens scribble is
            # overwritten by the first real insert, like everything
            # else prewarm touches).
            self._export_pages(self._cache, trash_row)
            zero_stacks = jax.tree.map(
                lambda leaf: jnp.zeros(
                    (self._pages_per_slot,) + tuple(leaf.shape[1:]),
                    leaf.dtype), self._cache)
            zero = jnp.zeros((), jnp.int32)
            (self._cache, self._last_d,
             self._lens_d) = self._adopt_insert(
                 self._cache, self._last_d, self._lens_d, zero_stacks,
                 trash_row, zero, zero, jnp.ones((), jnp.int32))
        if self._paged:
            _, self._cache, self._last_d, self._lens_d = self._decode(
                self.params, self._cache, self._pt(), self._last_d,
                self._lens_d, self._next_rng())
            if self._spec_k:
                # The verify program is the only other steady-state
                # shape: zero drafts against all-trash tables (every
                # write lands in the trash page; slot state is donated
                # back scribbled like the decode warm above).
                _, self._cache, self._last_d, self._lens_d = \
                    self._verify(
                        self.params, self._cache, self._pt(),
                        self._last_d, self._lens_d,
                        jnp.zeros((self.cfg.n_slots, self._spec_k),
                                  jnp.int32))
        else:
            _, self._cache, self._last_d, self._lens_d = self._decode(
                self.params, self._cache, self._last_d, self._lens_d,
                self._next_rng())

    def start(self):
        self._thread = threading.Thread(target=self._loop,
                                        name='decode-engine', daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    # ----- engine loop -------------------------------------------------------
    def _bucket(self, n: int) -> int:
        for b in self.cfg.prefill_buckets:
            if n <= b:
                return b
        raise ValueError(f'prompt len {n} exceeds buckets')

    def _next_rng(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _admit(self, slot_id: int, req: Request) -> None:
        """Single-request admission (tests/back-compat); batched path
        is _admit_group."""
        pages = None
        if self._paged:
            pages = self._alloc_pages(self._pages_needed(req))
            if pages is None:
                raise RuntimeError(
                    f'page pool exhausted: need '
                    f'{self._pages_needed(req)} pages, '
                    f'{self._pool_alloc.free_pages} free')
        self._admit_group(self._bucket(len(req.prompt_ids)),
                          [(slot_id, req, pages)])

    # ----- paged-KV host bookkeeping -----------------------------------------
    def _pages_needed(self, req: Request) -> int:
        """Pages this request is charged at admission: its WHOLE
        lifetime (prompt + full token budget), so mid-flight growth can
        never fail — the ceiling admission control enforces is pages,
        not slots."""
        return -(-(len(req.prompt_ids) + req.max_new_tokens)
                 // self._page_size)

    def _alloc_pages(self, n: int) -> Optional[List[int]]:
        """Allocate n pages, LRU-evicting prefix-cache pages if the
        free list runs short.  None (and no partial allocation) when
        even eviction cannot cover it — the caller retries once live
        slots retire."""
        pages = self._pool_alloc.alloc(n)
        if pages is None and self._radix is not None:
            freed = self._radix.evict(n - self._pool_alloc.free_pages)
            if freed:
                metrics_lib.inc_counter(
                    'skytpu_engine_prefix_cache_evicted_pages_total',
                    float(freed))
            pages = self._pool_alloc.alloc(n)
        return pages

    def _try_prefix_match(self, req: Request):
        """Match one request against the radix cache at its COMMIT
        point (admission / chunk pick — as late as possible, so a
        burst's later members hit pages its first member published).
        A hit refs the matched pages on the request's behalf and counts
        the hit metrics; the match is capped one token short of the
        prompt so there is always a suffix to prefill (the first output
        token is sampled from it).  Misses are counted by the caller
        when the request actually admits — a request re-examined while
        it waits for pages must not double-count."""
        max_pages = (len(req.prompt_ids) - 1) // self._page_size
        n, pages = self._radix.match(req.prompt_ids, max_pages)
        if n:
            metrics_lib.inc_counter(
                'skytpu_engine_prefix_cache_hits_total')
            metrics_lib.inc_counter(
                'skytpu_engine_prefix_cache_tokens_total',
                float(n * self._page_size))
        return n, pages

    def _route_queued(self) -> None:
        """Drain submitted short prompts into the loop's ready queue
        (prefix classification happens at admission time, against the
        trie as it stands THEN)."""
        while True:
            try:
                req = self._prefill_q.get_nowait()
            except queue.Empty:
                return
            self._ready_q.append(req)

    def _pt(self):
        """Device copy of the page tables, refreshed only when host
        bookkeeping changed (async H2D — never a sync)."""
        if self._pt_dirty or self._pt_device is None:
            self._pt_device = jnp.asarray(self._page_tables)
            self._pt_dirty = False
        return self._pt_device

    def _pt_row(self, pages: List[int]) -> np.ndarray:
        row = np.full((self._pages_per_slot,), TRASH_PAGE, np.int32)
        row[:len(pages)] = pages
        return row

    def _dispatch_decode(self):
        if self._spec_k:
            # Speculative step: k host-drafted tokens per slot, one
            # fixed-shape verify dispatch (same 4-tuple contract as
            # decode; the acceptance counts ride the output's last
            # row).  Greedy, so no rng.
            return self._verify(self.params, self._cache, self._pt(),
                                self._last_d, self._lens_d,
                                jnp.asarray(self._propose_drafts()))
        if self._paged:
            return self._decode(self.params, self._cache, self._pt(),
                                self._last_d, self._lens_d,
                                self._next_rng())
        return self._decode(self.params, self._cache, self._last_d,
                            self._lens_d, self._next_rng())

    def _propose_drafts(self) -> np.ndarray:
        """Host-side n-gram drafts [n_slots, k] for the next verify
        dispatch: each active slot's draft is the continuation of the
        most recent earlier occurrence of its own tail n-gram (self-
        speculation — no second model).  Empty/retired slots draft
        zeros against all-trash page tables; their acceptance counts
        are garbage the host never reads."""
        drafts = np.zeros((self.cfg.n_slots, self._spec_k), np.int32)
        for i, slot in enumerate(self._slots):
            if slot is None or slot.request is None:
                continue
            hist = slot.request.prompt_ids + slot.toks
            drafts[i] = _ngram_continuation(hist, self._spec_k)
        return drafts

    def _admit_group(self, bucket: int, group) -> None:
        """Dispatch ONE batched prefill+insert for all (slot, request,
        pages) triples of a bucket (pages is None on the unpaged
        engine); does NOT sync — each first token is emitted from row 0
        of the next decode call's output.

        The group is padded to a power-of-two row count (few compiled
        shapes: |buckets| x log2(n_slots)); padding replicates row 0,
        whose duplicate scatter writes are identical-value no-ops.
        """
        n = len(group)
        padded_n = 1 << (n - 1).bit_length()
        tokens = np.zeros((padded_n, bucket), np.int32)
        lengths = np.zeros((padded_n,), np.int32)
        slots = np.zeros((padded_n,), np.int32)
        valid = np.zeros((padded_n,), np.int32)
        pt_rows = (np.full((padded_n, self._pages_per_slot), TRASH_PAGE,
                           np.int32) if self._paged else None)
        for j, (slot_id, req, pages) in enumerate(group):
            plen = len(req.prompt_ids)
            tokens[j, :plen] = req.prompt_ids
            lengths[j] = plen
            slots[j] = slot_id
            valid[j] = 1
            if pages is not None:
                pt_rows[j, :len(pages)] = pages
        tokens[n:] = tokens[0]
        lengths[n:] = lengths[0]
        slots[n:] = slots[0]
        if pt_rows is not None:
            pt_rows[n:] = pt_rows[0]
        prefill = self._prefill_for(bucket, padded_n)
        t0 = time.perf_counter()
        if self._paged:
            self._cache, self._last_d, self._lens_d = prefill(
                self.params, self._cache, self._last_d, self._lens_d,
                jnp.asarray(tokens), jnp.asarray(lengths),
                jnp.asarray(slots), jnp.asarray(pt_rows),
                jnp.asarray(valid), self._next_rng())
        else:
            self._cache, self._last_d, self._lens_d = prefill(
                self.params, self._cache, self._last_d, self._lens_d,
                jnp.asarray(tokens), jnp.asarray(lengths),
                jnp.asarray(slots), jnp.asarray(valid), self._next_rng())
        t1 = time.perf_counter()
        for j, (slot_id, req, pages) in enumerate(group):
            self._slots[slot_id] = _Slot(req, len(req.prompt_ids),
                                         pages=pages)
            if self._paged:
                self._page_tables[slot_id] = pt_rows[j]
                self._pt_dirty = True
                if self._radix is not None:
                    # Publish the prompt's full pages immediately:
                    # concurrent requests sharing the prefix hit from
                    # here on (the writes they gather are already
                    # queued ahead of them on device).
                    n_full = len(req.prompt_ids) // self._page_size
                    if n_full:
                        self._radix.insert(
                            req.prompt_ids[:n_full * self._page_size],
                            pages[:n_full])
            if req.request_id is not None:
                # Host-side stamps only (the dispatch is async): the
                # spans tile [submit, prefill-dispatch end]; the
                # engine.dispatch span picks up from prefill_end_at.
                # Spill-demoted requests recorded queue_wait on their
                # original hit path — never twice.
                if not req.no_prefix:
                    tracing.record_span(req.request_id,
                                        'engine.queue_wait',
                                        req.submitted_at, t0)
                tracing.record_span(req.request_id, 'engine.prefill',
                                    t0, t1, bucket=bucket, slot=slot_id,
                                    group=len(group))
                req.prefill_end_at = t1
        n_tokens = sum(len(r.prompt_ids) for _, r, _pg in group)
        with self._submit_lock:
            self._queued_tokens -= n_tokens
        metrics_lib.inc_counter('skytpu_engine_prefill_tokens_total',
                                float(n_tokens))
        if self._kv_quant:
            # Real (non-trash) pages quantized at this insert's scatter.
            metrics_lib.inc_counter(
                'skytpu_engine_kv_quant_pages_total',
                float(sum(len(pg) for _, _r, pg in group)))

    def _emit(self, req: Request, tok: int) -> None:
        req.emitted += 1
        req.out.put(tok)

    def _finished(self, slot: _Slot, tok: int) -> bool:
        return (tok == self.cfg.eos_id or
                slot.request.emitted >= slot.request.max_new_tokens)

    def _retire(self, slot_id: int, slot: Optional[_Slot] = None) -> None:
        slot = slot if slot is not None else self._slots[slot_id]
        slot.done = True
        req = slot.request
        req.finished_at = time.perf_counter()
        # Mean inter-token latency over the request's decode phase —
        # host-side perf_counter stamps only, no device sync.
        if req.first_token_at is not None and req.emitted > 1:
            metrics_lib.observe_hist(
                metrics_lib.ENGINE_TPOT_FAMILY,
                (req.finished_at - req.first_token_at) /
                (req.emitted - 1))
        if req.request_id is not None:
            tracing.record_instant(
                req.request_id, 'engine.stream_end', req.finished_at,
                emitted=req.emitted,
                decode_s=(round(req.finished_at - req.first_token_at, 6)
                          if req.first_token_at is not None else None))
        if req.export and slot.pages is not None:
            # Stage the KV handoff BEFORE the terminating None: a
            # caller whose tokens() returned may immediately read
            # export_result.  The gather dispatch also precedes this
            # retire's page release, so any later scatter into the
            # freed pages is ordered behind it on device.
            self._dispatch_export(slot)
        req.out.put(None)
        if slot.pages is not None:
            self._release_slot_pages(slot)
            # Point the slot's table at trash so later decode calls
            # cannot scribble into pages a new owner holds — unless a
            # handoff successor already owns the row.
            if self._slots[slot_id] is slot:
                self._page_tables[slot_id] = TRASH_PAGE
                self._pt_dirty = True
        # Under handoff a successor may already occupy the index — only
        # clear the mapping when it still points at the finished slot.
        if self._slots[slot_id] is slot:
            self._slots[slot_id] = None

    def _release_slot_pages(self, slot: _Slot) -> None:
        """Retire-time page bookkeeping: donate the pages covering the
        finished sequence (prompt + generated tokens whose KV was
        written — every emitted token except the last fed a later step)
        to the radix cache, then drop this slot's references.  Shared
        prefix pages return to their other holders; owned pages either
        live on in the cache (multi-turn replays of prompt+reply hit
        them) or free."""
        req = slot.request
        if self._radix is not None:
            usable = len(req.prompt_ids) + req.emitted - 1
            n_full = min(usable // self._page_size, len(slot.pages))
            if n_full > 0:
                seq = req.prompt_ids + slot.toks
                self._radix.insert(seq[:n_full * self._page_size],
                                   slot.pages[:n_full])
        self._pool_alloc.release(slot.pages)
        slot.pages = None

    def _dispatch_export(self, slot: _Slot) -> None:
        """Stage a prefill-role request's pages for transfer: ONE
        gather dispatch off the (read-only) pool, queued on device
        ahead of this retire's page release — any later scatter into
        the freed pages is ordered behind it, so the gathered values
        are pre-overwrite by construction.  Only device ARRAYS land on
        the Request here; the HTTP layer syncs them on ITS thread
        (export_result) — the loop thread never blocks on the
        device->host copy."""
        req = slot.request
        t0 = time.perf_counter()
        leaves = self._export_pages(
            self._cache, jnp.asarray(self._pt_row(slot.pages)))
        t1 = time.perf_counter()
        n_kv = -(-len(req.prompt_ids) // self._page_size)
        req.kv_export = {
            'leaves': leaves,
            'first_token': int(slot.toks[0]) if slot.toks else 0,
            'prompt_len': len(req.prompt_ids),
            'n_kv_pages': n_kv,
        }
        metrics_lib.inc_counter('skytpu_engine_kv_exports_total')
        if req.request_id is not None:
            tracing.record_span(req.request_id, 'engine.kv_export',
                                t0, t1, pages=n_kv)

    def _step_adopt(self) -> None:
        """Admit pending KV-handoff adoptions (decode role) into free
        slots: allocate the request's full-lifetime pages — admission
        charges ceil((prompt+max_new)/page) exactly like a local
        prefill — scatter the transferred page stacks into them in ONE
        fixed-shape dispatch, and seed the slot's last token / length
        from the handoff.  Head-of-line on slot or page shortage;
        retiring slots free both in order."""
        if not self._paged:
            return
        while True:
            try:
                self._adopt_ready.append(self._adopt_q.get_nowait())
            except queue.Empty:
                break
        while self._adopt_ready:
            slot_id = next((i for i in range(self.cfg.n_slots)
                            if self._slots[i] is None), None)
            if slot_id is None:
                return
            req = self._adopt_ready[0]
            pages = self._alloc_pages(self._pages_needed(req))
            if pages is None:
                return
            self._adopt_ready.popleft()
            first_token, kv_leaves = req.adopt
            n_kv = kv_leaves[0].shape[0]
            t0 = time.perf_counter()
            # Full-height page stacks (pages_per_slot rows) keep the
            # adopt program at ONE compiled shape; rows past the
            # transfer are zeros and scatter into the trash page.
            padded = []
            for leaf in kv_leaves:
                buf = np.zeros(
                    (self._pages_per_slot,) + tuple(leaf.shape[1:]),
                    leaf.dtype)
                buf[:n_kv] = leaf
                padded.append(jnp.asarray(buf))
            data = jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(self._cache), padded)
            scatter_row = np.full((self._pages_per_slot,), TRASH_PAGE,
                                  np.int32)
            scatter_row[:n_kv] = pages[:n_kv]
            row = self._pt_row(pages)
            (self._cache, self._last_d,
             self._lens_d) = self._adopt_insert(
                 self._cache, self._last_d, self._lens_d, data,
                 jnp.asarray(scatter_row),
                 jnp.asarray(slot_id, jnp.int32),
                 jnp.asarray(first_token, jnp.int32),
                 jnp.asarray(len(req.prompt_ids), jnp.int32))
            t1 = time.perf_counter()
            self._slots[slot_id] = _Slot(req, len(req.prompt_ids),
                                         pages=pages)
            self._page_tables[slot_id] = row
            self._pt_dirty = True
            if self._radix is not None:
                # Adopted prompt pages join the radix cache like
                # locally prefilled ones: decode-pool multi-turn
                # replays hit through the transferred prefix.  Full
                # pages only — decode writes land strictly past them.
                n_full = len(req.prompt_ids) // self._page_size
                if n_full:
                    self._radix.insert(
                        req.prompt_ids[:n_full * self._page_size],
                        pages[:n_full])
            metrics_lib.inc_counter('skytpu_engine_kv_adopts_total')
            if self._kv_quant:
                metrics_lib.inc_counter(
                    'skytpu_engine_kv_quant_pages_total', float(n_kv))
            if req.request_id is not None:
                tracing.record_span(req.request_id, 'engine.queue_wait',
                                    req.submitted_at, t0)
                tracing.record_span(req.request_id, 'engine.kv_adopt',
                                    t0, t1, slot=slot_id, pages=n_kv)
            req.prefill_end_at = t1

    def _admit_free(self, handoff: Optional[List[int]] = None) -> None:
        """Admit queued requests into free slots (grouped per bucket —
        one fused prefill dispatch per group).  ``handoff`` lists slot
        indices whose occupant is guaranteed to finish during the
        IN-FLIGHT decode call: their successors' prefill+insert queues
        behind that call on device, so the slot turns over with zero
        garbage calls (the in-flight snapshot still emits the finishing
        occupant's rows — see _Slot.done)."""
        free = [i for i in range(self.cfg.n_slots)
                if self._slots[i] is None]
        free += [i for i in (handoff or []) if self._slots[i] is not None]
        if free and self._final_insert_pending():
            # Reserve one slot for the active long prompt's final
            # chunk-insert (it claims a slot in _step_chunked, which
            # runs BEFORE the next admission): under sustained short
            # traffic, handing every freed slot to _prefill_q would
            # starve the insert forever — unbounded long-prompt TTFT.
            # pop(0): prefer reserving a truly-free slot (the list's
            # head) so the insert can claim it immediately; handoff
            # slots at the tail only free after the in-flight call.
            free.pop(0)
        by_bucket: Dict[int, list] = {}
        if self._paged:
            # Prefix-cache routing first, then admission charges PAGES:
            # a request admits only when its whole lifetime fits the
            # pool (evicting cached pages as needed).  Head-of-line on
            # allocation failure — retiring slots free pages in order.
            self._route_queued()
            while free and self._ready_q:
                req = self._ready_q[0]
                if self._radix is not None and not req.no_prefix:
                    n, pages = self._try_prefix_match(req)
                    if n:
                        # Hit: the suffix prefills through the chunk
                        # machinery against the gathered prefix — no
                        # slot consumed here.
                        self._ready_q.popleft()
                        self._hit_q.append((req, n, pages))
                        continue
                pages = self._alloc_pages(self._pages_needed(req))
                if pages is None:
                    break
                self._ready_q.popleft()
                if self._radix is not None and not req.no_prefix:
                    # A spill-demoted request already counted its hit;
                    # counting a miss too would skew the hit rate.
                    metrics_lib.inc_counter(
                        'skytpu_engine_prefix_cache_misses_total')
                by_bucket.setdefault(
                    self._bucket(len(req.prompt_ids)), []).append(
                        (free.pop(0), req, pages))
        else:
            while free and not self._prefill_q.empty():
                try:
                    req = self._prefill_q.get_nowait()
                except queue.Empty:
                    break
                by_bucket.setdefault(
                    self._bucket(len(req.prompt_ids)), []).append(
                        (free.pop(0), req, None))
        for bucket, group in by_bucket.items():
            self._admit_group(bucket, group)

    def _final_insert_pending(self) -> bool:
        """True when the active chunked prefill has reached its final
        chunk and is waiting on a free slot to insert into (a pending
        prefix-cache hit counts: its suffix needs a slot just as
        soon)."""
        cp = self._chunked
        if cp is None:
            return bool(self._hit_q)
        return (len(cp.request.prompt_ids) - cp.offset
                <= self.cfg.prefill_buckets[-1])

    def _start_chunked(self) -> bool:
        """Activate the next request for the chunk machinery: a pending
        prefix-cache hit first (its matched pages gather into a seeded
        scratch and the prefill starts PAST the match — the skipped
        work is the prefix cache's whole point), else the next long
        prompt (itself prefix-matched when the cache is on)."""
        matched, pages = 0, []
        if self._hit_q:
            req, matched, pages = self._hit_q.popleft()
        else:
            try:
                req = self._long_q.get_nowait()
            except queue.Empty:
                return False
            if self._radix is not None and not req.no_prefix:
                matched, pages = self._try_prefix_match(req)
                if not matched:
                    metrics_lib.inc_counter(
                        'skytpu_engine_prefix_cache_misses_total')
        if not matched:
            self._chunked = _ChunkedPrefill(req, self._new_scratch())
            return True
        t0 = time.perf_counter()
        scratch = self._gather_prefix(self._cache,
                                      jnp.asarray(self._pt_row(pages)))
        t1 = time.perf_counter()
        offset = matched * self._page_size
        cp = _ChunkedPrefill(req, scratch, offset=offset,
                             shared_pages=pages)
        cp.last_chunk_end = t1
        self._chunked = cp
        rid = req.request_id
        if rid is not None:
            tracing.record_span(rid, 'engine.queue_wait',
                                req.submitted_at, t0)
            tracing.record_span(rid, 'engine.prefix_hit', t0, t1,
                                cached_tokens=offset, pages=matched)
        with self._submit_lock:
            self._queued_tokens -= offset
        return True

    def _spill_stuck_hits(self) -> None:
        """Release every pinned prefix match (the active seeded prefill
        and all waiting hits) and requeue the requests for FULL
        prefill.  Only reachable when a final insert cannot allocate
        with zero live slots — a pool sized near its floor — so
        correctness (progress) wins over reuse."""
        cp = self._chunked
        if cp is not None and cp.shared_pages:
            self._pool_alloc.release(cp.shared_pages)
            # Restart from token zero with a fresh scratch next pick.
            self._chunked = None
            cp.request.no_prefix = True
            self._long_q.put(cp.request)
            with self._submit_lock:
                self._queued_tokens += cp.offset
        while self._hit_q:
            req, _n, pages = self._hit_q.popleft()
            self._pool_alloc.release(pages)
            req.no_prefix = True
            self._ready_q.appendleft(req)

    def _step_chunked(self) -> bool:
        """Dispatch at most ONE chunk of the active long-prompt
        prefill.  Called once per loop iteration right after the decode
        dispatch, so on device the order is decode, chunk, decode,
        chunk, ... — the decode batch is never delayed by more than one
        chunk-sized call however long the prompt is.  Intermediate
        chunks are largest-bucket-wide; the final chunk pads to the
        smallest fitting bucket, samples the first token and inserts
        the scratch cache into a free slot (waiting for one to retire
        if none is free — decode keeps running meanwhile).  Returns
        True if a dispatch was made."""
        if self._chunked is None and not self._start_chunked():
            return False
        cp = self._chunked
        prompt = cp.request.prompt_ids
        rem = len(prompt) - cp.offset
        chunk = self.cfg.prefill_buckets[-1]
        rid = cp.request.request_id
        if rem > chunk:
            t0 = time.perf_counter()
            buf = np.zeros((1, chunk), np.int32)
            buf[0] = prompt[cp.offset:cp.offset + chunk]
            cp.scratch = self._chunk_for(chunk)(
                self.params, cp.scratch, jnp.asarray(buf),
                jnp.asarray(cp.offset, jnp.int32))
            t1 = time.perf_counter()
            if rid is not None:
                if cp.offset == 0 and not cp.request.no_prefix:
                    # (A spill-demoted request recorded its queue_wait
                    # in the hit path already — the discarded gather's
                    # span stays as what actually happened, and the
                    # restart gap reads as unattributed time.)
                    tracing.record_span(rid, 'engine.queue_wait',
                                        cp.request.submitted_at, t0)
                tracing.record_span(
                    rid, 'engine.prefill_chunk',
                    cp.last_chunk_end if cp.last_chunk_end is not None
                    else t0,
                    t1, offset=cp.offset, width=chunk, final=False)
            cp.last_chunk_end = t1
            cp.offset += chunk
            done = chunk
        else:
            slot_id = next((i for i in range(self.cfg.n_slots)
                            if self._slots[i] is None), None)
            if slot_id is None:
                return False             # all slots busy: retry later
            pages_all, n_shared, row = None, 0, None
            if self._paged:
                n_shared = len(cp.shared_pages)
                owned = self._alloc_pages(
                    self._pages_needed(cp.request) - n_shared)
                if owned is None:
                    if (self._inflight is None and
                            all(s is None for s in self._slots)):
                        # Nothing live can ever free a page: the pool
                        # is pinned by waiting prefix matches (tiny
                        # kv_pages).  Drop every pinned match and fall
                        # back to full prefills — slower, never stuck.
                        self._spill_stuck_hits()
                    return False         # pool short: retry next iter
                pages_all = cp.shared_pages + owned
                row = self._pt_row(pages_all)
            bucket = self._bucket(rem)
            t0 = time.perf_counter()
            buf = np.zeros((1, bucket), np.int32)
            buf[0, :rem] = prompt[cp.offset:]
            if self._paged:
                (self._cache, self._last_d,
                 self._lens_d) = self._chunk_insert(
                     self.params, self._cache, self._last_d, self._lens_d,
                     cp.scratch, jnp.asarray(buf),
                     jnp.asarray(rem, jnp.int32),
                     jnp.asarray(cp.offset, jnp.int32),
                     jnp.asarray(len(prompt), jnp.int32),
                     jnp.asarray(slot_id, jnp.int32), jnp.asarray(row),
                     self._next_rng())
            else:
                (self._cache, self._last_d,
                 self._lens_d) = self._chunk_insert_for(bucket)(
                     self.params, self._cache, self._last_d, self._lens_d,
                     cp.scratch, jnp.asarray(buf),
                     jnp.asarray(rem, jnp.int32),
                     jnp.asarray(cp.offset, jnp.int32),
                     jnp.asarray(len(prompt), jnp.int32),
                     jnp.asarray(slot_id, jnp.int32), self._next_rng())
            t1 = time.perf_counter()
            if rid is not None:
                # queue_wait was recorded by the FIRST chunk, which is
                # always an intermediate one (only prompts longer than
                # the largest bucket chunk, so rem > chunk at offset
                # 0).  The final-chunk span includes any wait for a
                # free slot.
                tracing.record_span(
                    rid, 'engine.prefill_chunk',
                    cp.last_chunk_end if cp.last_chunk_end is not None
                    else t0,
                    t1, offset=cp.offset, width=bucket, final=True,
                    slot=slot_id)
                cp.request.prefill_end_at = t1
            self._slots[slot_id] = _Slot(cp.request, len(prompt),
                                         pages=pages_all,
                                         n_shared=n_shared)
            if self._paged:
                self._page_tables[slot_id] = row
                self._pt_dirty = True
                if self._radix is not None:
                    n_full = len(prompt) // self._page_size
                    if n_full:
                        self._radix.insert(
                            prompt[:n_full * self._page_size],
                            pages_all[:n_full])
            self._chunked = None
            done = rem
            if self._kv_quant and pages_all is not None:
                metrics_lib.inc_counter(
                    'skytpu_engine_kv_quant_pages_total',
                    float(len(pages_all)))
        with self._submit_lock:
            self._queued_tokens -= done
        metrics_lib.inc_counter('skytpu_engine_prefill_chunks_total')
        metrics_lib.inc_counter('skytpu_engine_prefill_tokens_total',
                                float(done))
        return True

    def _sample_perf(self, n_active: int) -> None:
        """Loop-thread device-cost gauges (perf/cost_model.py): pure
        host arithmetic over _process_rows' emit accumulators — no
        device state is touched, so attribution adds ZERO syncs
        (test-enforced).  Windowed at perf_window_s so the idle 1 kHz
        loop does not recompute rates every millisecond."""
        cm = self._cost_model
        if cm is None:
            return
        now = time.perf_counter()
        if self._perf_window is None:
            self._perf_window = (now, self._perf_tokens,
                                 self._perf_ctx_sum, self._perf_occ_sum)
            return
        t0, tok0, ctx0, occ0 = self._perf_window
        if now - t0 < self.perf_window_s:
            return
        d_tok = self._perf_tokens - tok0
        self._perf_window = (now, self._perf_tokens, self._perf_ctx_sum,
                             self._perf_occ_sum)
        if d_tok <= 0:
            # Idle window: utilization is genuinely zero; the modeled
            # bytes/intensity gauges keep their last value (they
            # describe the workload shape, not the rate).
            if self._perf_last is not None and self._perf_last['mfu']:
                self._perf_last = dict(self._perf_last, mfu=0.0)
                metrics_lib.set_gauge('skytpu_engine_mfu', 0.0)
            return
        rate = d_tok / (now - t0)
        # Token-weighted means over the window: each emitted token
        # contributed its slot's context length and its decode call's
        # batch size.
        mean_ctx = (self._perf_ctx_sum - ctx0) / d_tok
        mean_occ = max(1.0, (self._perf_occ_sum - occ0) / d_tok)
        mfu = cm.mfu(rate, mean_ctx)
        hbm_bytes = cm.decode_hbm_bytes_per_token(mean_ctx, mean_occ)
        intensity = cm.arith_intensity(mean_ctx, mean_occ)
        self._perf_last = {
            'mfu': mfu, 'hbm_bytes_per_token': hbm_bytes,
            'arith_intensity': intensity, 'tokens_per_s': rate,
            'mean_context': mean_ctx, 'mean_occupancy': mean_occ,
        }
        metrics_lib.set_gauge('skytpu_engine_mfu', mfu)
        metrics_lib.set_gauge('skytpu_engine_hbm_bytes_per_token',
                              hbm_bytes)
        metrics_lib.set_gauge('skytpu_engine_arith_intensity', intensity)

    def _sample_gauges(self, n_active: int) -> None:
        """Loop-thread occupancy/queue gauges; skipped when unchanged so
        the idle 1 kHz loop does not hammer the registry lock."""
        self._sample_perf(n_active)
        sample = (n_active,
                  self._prefill_q.qsize() + self._long_q.qsize() +
                  len(self._ready_q) + len(self._hit_q) +
                  self._adopt_q.qsize() + len(self._adopt_ready),
                  self._queued_tokens,
                  self._pool_alloc.free_pages if self._paged else -1,
                  self._radix.fingerprint
                  if self._radix is not None else None)
        if sample == self._last_gauges:
            return
        self._last_gauges = sample
        if self._paged:
            metrics_lib.set_gauge('skytpu_engine_kv_free_pages',
                                  float(sample[3]))
        if sample[4] is not None:
            # Prefix-set identity of this replica's radix cache: the
            # controller's scrape ingests it per replica, so affinity
            # routing (ROADMAP item 2) can group replicas by content.
            metrics_lib.set_gauge('skytpu_engine_prefix_fingerprint',
                                  float(sample[4]))
        metrics_lib.set_gauge('skytpu_engine_active_slots',
                              float(n_active))
        metrics_lib.set_gauge('skytpu_engine_batch_occupancy_ratio',
                              n_active / self.cfg.n_slots)
        metrics_lib.set_gauge('skytpu_engine_queue_depth',
                              float(sample[1]))
        # Long-prompt backlog: tokens accepted but not yet prefilled
        # (the LB federates this per replica, so a scrape sees where
        # chunked prefills are queueing up).
        metrics_lib.set_gauge(metrics_lib.QUEUED_PREFILL_TOKENS_FAMILY,
                              float(max(sample[2], 0)))

    def step(self) -> int:  # skytpu: hot-entry
        """One SYNCHRONOUS engine iteration (admit + decode + process).
        Returns #active slots.  Exposed for tests and debugging; the
        serving loop and benchmarks use step_pipelined, which overlaps
        the host work with the next device call."""
        self._install_staged()
        self._step_chunked()
        self._step_adopt()
        self._admit_free()
        active = [i for i in range(self.cfg.n_slots)
                  if self._slots[i] is not None]
        self._sample_gauges(len(active))
        if not active:
            self._release_retiring()
            return 0
        t0 = time.perf_counter()
        out, self._cache, self._last_d, self._lens_d = \
            self._dispatch_decode()
        # skytpu: allow-sync(the ONE device->host fetch per step — the engine's contract)
        out = np.asarray(out)            # [T+1, B] — the ONE sync per step
        t1 = time.perf_counter()
        snapshot = {i: self._slots[i] for i in active}
        if self._spec_k:
            # Speculative verify: the last output row is the per-slot
            # acceptance count m (1..k+1) — rows 1..m are committed
            # tokens, rows past m are rejected drafts' garbage.
            self._process_rows(out[:-1], snapshot, counts=out[-1],
                               verify_span=(t0, t1))
        else:
            self._process_rows(out, snapshot)
        self._release_retiring()
        return len(active)

    def step_pipelined(self) -> int:  # skytpu: hot-entry
        """One PIPELINED iteration: dispatch decode call k, THEN sync and
        process call k-1's output while k runs on device, then admit
        into any slots k-1 freed (their prefills queue behind k).

        The device therefore never idles between calls — the host's
        token emission, retire bookkeeping and the dispatch round-trip
        (about a full RPC on tunneled control planes) all hide under
        call k's compute.  The price is a one-call lag: a slot that
        finishes inside call k keeps decoding garbage through call k+1
        (discarded by _process_rows' snapshot identity check, bounded at
        steps_per_call tokens), and an admission waits one extra call
        before its first token.  At saturation the throughput win
        dominates; TTFT under light load pays ~one call of latency.

        Staged weight swaps install at the TOP of the iteration — the
        dispatch boundary: the call dispatched below and everything
        after it runs the new tree, and the old tree is released right
        after the in-flight sync (the last point a call dispatched
        against it can retire behind).  A long prompt's chunked prefill
        dispatches at most one chunk per iteration, right behind the
        decode call, so decode is interleaved chunk-by-chunk instead of
        stalling behind the whole prefill.

        Returns #slots active in the dispatched call plus any chunk
        dispatched (0 = fully idle and nothing in flight).
        """
        if self._spec_k:
            # Speculation replaces pipelining: dispatching call k's
            # drafts before call k-1's tokens land would draft from
            # one-call-stale history and collapse acceptance.  The
            # multi-token verify dispatch is the latency-hiding lever
            # instead; step() keeps the same admission/chunked/adopt
            # machinery and the one-sync contract.
            return self.step()
        self._install_staged()
        active = [i for i in range(self.cfg.n_slots)
                  if self._slots[i] is not None]
        self._sample_gauges(len(active))
        dispatched = None
        if active:
            out_d, self._cache, self._last_d, self._lens_d = \
                self._dispatch_decode()
            dispatched = (out_d, {i: self._slots[i] for i in active})
        chunked = self._step_chunked()   # queues behind the decode call
        if self._inflight is not None:
            out_prev, snapshot = self._inflight
            self._inflight = None
            # skytpu: allow-sync(the ONE fetch per step, one call late: syncs call k-1 while call k runs)
            self._process_rows(np.asarray(out_prev), snapshot)
        self._release_retiring()
        self._inflight = dispatched
        # Admissions AFTER processing: retired slots are free now, and
        # slots whose occupant will PROVABLY finish inside the call just
        # dispatched (its remaining max_new fits the rows that call
        # delivers) hand off to a successor with zero garbage calls —
        # the successor's prefill queues behind the in-flight call.
        handoff = []
        if dispatched is not None:
            steps = self.cfg.steps_per_call
            for i, slot in dispatched[1].items():
                if self._slots[i] is not slot or slot.done:
                    continue
                rows_to_come = steps + (1 if slot.first_pending else 0)
                remaining = (slot.request.max_new_tokens -
                             slot.request.emitted)
                if remaining <= rows_to_come:
                    handoff.append(i)
        self._step_adopt()
        self._admit_free(handoff)
        return len(active) + (1 if chunked else 0)

    def _process_rows(self, out: np.ndarray, snapshot: Dict[int, _Slot],
                      counts: Optional[np.ndarray] = None,
                      verify_span: Optional[tuple] = None) -> None:
        """Emit one decode call's tokens to the slots captured at its
        DISPATCH time.  A slot whose occupant changed since (retired, or
        retired-and-readmitted under pipelining) is skipped by object
        identity — its rows are the bounded garbage of the one-call
        retire lag, never another request's tokens.

        ``counts`` (speculative verify calls): the per-slot acceptance
        count m — only rows 1..m of ``out`` are committed tokens for
        slot i; the rest are rejected drafts.  ``verify_span`` is the
        (dispatch, fetch) perf_counter bracket for the engine.verify
        flight-recorder span of traced requests."""
        now = time.perf_counter()
        emitted = 0
        spec_proposed = spec_accepted = 0
        for i, slot in snapshot.items():
            if slot.done:
                continue                 # retired earlier: rows are garbage
            limit = out.shape[0]
            if counts is not None:
                m = int(counts[i])
                limit = min(m + 1, out.shape[0])
                spec_proposed += self._spec_k
                spec_accepted += m - 1
                rid = slot.request.request_id
                if rid is not None and verify_span is not None:
                    tracing.record_span(
                        rid, 'engine.verify', verify_span[0],
                        verify_span[1], slot=i,
                        proposed=self._spec_k, accepted=m - 1)
            start = 0
            if slot.first_pending:
                slot.first_pending = False
                slot.request.first_token_at = now
                metrics_lib.observe_hist(
                    metrics_lib.ENGINE_TTFT_FAMILY,
                    now - slot.request.submitted_at)
                rid = slot.request.request_id
                if rid is not None:
                    # The decode call the first token rode: from the
                    # prefill dispatch's end to the host observing the
                    # token — closes the TTFT tiling.
                    tracing.record_span(
                        rid, 'engine.dispatch',
                        slot.request.prefill_end_at
                        if slot.request.prefill_end_at is not None
                        else slot.request.submitted_at,
                        now, slot=i)
                    # Decode-batch membership + the measured TTFT the
                    # decomposition is checked against.
                    tracing.record_instant(
                        rid, 'engine.first_token', now, slot=i,
                        batch=len(snapshot),
                        ttft_s=round(now - slot.request.submitted_at,
                                     6))
            else:
                start = 1                # row 0 was emitted last step
            for t in range(start, limit):
                tok = int(out[t, i])
                slot.length += 1
                # Device-cost attribution: this token's context length
                # and decode-batch size (token-weighted accumulators
                # _sample_perf folds into the live gauges).
                self._perf_tokens += 1
                self._perf_ctx_sum += slot.length
                self._perf_occ_sum += len(snapshot)
                if slot.pages is not None:
                    # Retire donates prompt+generated pages to the
                    # prefix cache (it needs the generated token ids)
                    # and a prefill-role request's KV export needs its
                    # sampled first token.
                    slot.toks.append(tok)
                self._emit(slot.request, tok)
                emitted += 1
                if self._finished(slot, tok):
                    self._retire(i, slot)
                    break                # rest of this call's tokens: waste
        if emitted:
            metrics_lib.inc_counter('skytpu_engine_decode_tokens_total',
                                    float(emitted))
        if spec_proposed:
            metrics_lib.inc_counter(
                'skytpu_engine_spec_proposed_tokens_total',
                float(spec_proposed))
            metrics_lib.inc_counter(
                'skytpu_engine_spec_accepted_tokens_total',
                float(spec_accepted))
            metrics_lib.set_gauge('skytpu_engine_spec_acceptance',
                                  spec_accepted / spec_proposed)


    def _loop(self):  # skytpu: hot-entry
        while not self._stop.is_set():
            try:
                n = self.step_pipelined()
            except BaseException as e:  # pylint: disable=broad-except
                # A dead loop thread must not strand callers: fail every
                # in-flight and queued request, flip unhealthy (the HTTP
                # server's /health reports it, so serve's readiness
                # probes replace this replica).
                logger.exception('decode engine loop crashed')
                with self._submit_lock:
                    self.error = e
                    # Fail the in-flight snapshot FIRST: a handed-off
                    # slot's old occupant lives only there (replaced in
                    # _slots but not finished) and would otherwise
                    # strand its caller in Request.tokens() forever.
                    if self._inflight is not None:
                        for slot in self._inflight[1].values():
                            if not slot.done:
                                slot.done = True
                                slot.request.finished_at = \
                                    time.perf_counter()
                                slot.request.out.put(None)
                        self._inflight = None
                    for i, slot in enumerate(self._slots):
                        if slot is not None and not slot.done:
                            slot.done = True
                            slot.request.finished_at = time.perf_counter()
                            slot.request.out.put(None)
                        self._slots[i] = None
                    if self._chunked is not None:
                        cp, self._chunked = self._chunked, None
                        cp.request.finished_at = time.perf_counter()
                        cp.request.out.put(None)
                    for req in list(self._ready_q) + \
                            [h[0] for h in self._hit_q] + \
                            list(self._adopt_ready):
                        req.finished_at = time.perf_counter()
                        req.out.put(None)
                    self._ready_q.clear()
                    self._hit_q.clear()
                    self._adopt_ready.clear()
                    for pending in (self._prefill_q, self._long_q,
                                    self._adopt_q):
                        while True:
                            try:
                                req = pending.get_nowait()
                            except queue.Empty:
                                break
                            req.finished_at = time.perf_counter()
                            req.out.put(None)
                    self._queued_tokens = 0
                return
            if n == 0:
                time.sleep(0.001)
