"""Continuous-batching decode engine (JetStream twin).

The reference's serving baseline is JetStream driven through a recipe
YAML (examples/tpu/v6e/serve-llama2-7b.yaml; numbers at
examples/tpu/v6e/README.md:119-127).  This is the first-party TPU-native
equivalent, built on the same architecture JetStream proved out:

- a fixed pool of decode *slots*; every decode call is ONE jitted
  dispatch over the whole [n_slots] batch (batched matmuls keep the MXU
  busy and amortize the HBM weight sweep — decode is bandwidth-bound, so
  tokens/s scales almost linearly with occupied slots);
- each dispatch runs `steps_per_call` decode steps under `lax.scan`, so
  the host<->device round-trip (which can be ~100 ms on tunneled control
  planes) is amortized over T tokens per slot, not paid per token;
- the engine performs exactly ONE device->host sync per step: last
  tokens and lengths live on device, prefill+insert is a single fused
  dispatch whose sampled first token stays on device, and the decode
  call returns [T+1, n_slots] with row 0 = each slot's previously
  sampled token — so a freshly admitted request's first token rides the
  same fetch as the decode tokens;
- prefill runs per-request at bucket-padded lengths (few distinct
  compiled shapes), then the request's KV cache is *inserted* into its
  slot of the big cache in one device-side copy;
- the host loop only orchestrates: admit prefills into free slots, call
  the decode step, stream sampled tokens out, retire finished slots.
  Tokens a slot produces past its own EOS/max within a multi-step call
  are discarded host-side (bounded waste, never wrong output: a retiring
  slot's cache is fully overwritten by the next insert).

Static shapes throughout: the decode step never recompiles, prompts
compile once per bucket.  Slot safety relies on the model cache's
invariant (models/llama.py _decode_attend): attention masks k_pos >
q_pos, and inserts overwrite a slot's whole cache, so a reused slot never
leaks its previous request's KV.
"""
from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    n_slots: int = 8
    # Prompt lengths are padded up to one of these (each bucket compiles
    # once).  Longest bucket bounds admissible prompts.
    prefill_buckets: tuple = (32, 64, 128, 256, 512)
    # Decode steps per jitted dispatch (lax.scan trip count).  Larger
    # values amortize host<->device latency; smaller values tighten the
    # admission/streaming granularity.
    steps_per_call: int = 8
    eos_id: Optional[int] = None       # None: never stop on a token
    temperature: float = 0.0           # 0 => greedy
    seed: int = 0


@dataclasses.dataclass
class Request:
    prompt_ids: List[int]
    max_new_tokens: int
    out: 'queue.Queue[Optional[int]]' = dataclasses.field(
        default_factory=queue.Queue)
    submitted_at: float = dataclasses.field(default_factory=time.perf_counter)
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    emitted: int = 0

    def tokens(self) -> List[int]:
        """Drain: block until the request finishes, return all tokens."""
        toks = []
        while True:
            t = self.out.get()
            if t is None:
                return toks
            toks.append(t)


class _Slot:
    __slots__ = ('request', 'length', 'first_pending')

    def __init__(self, request: Request, length: int) -> None:
        self.request = request
        self.length = length              # prompt len + emitted (host view)
        # True until the prefill-sampled first token has been emitted
        # (it arrives as row 0 of the next decode call's output).
        self.first_pending = True


class DecodeEngine:
    """Slot-based continuous batching over a Llama-family model.

    `model.cfg.max_seq_len` bounds prompt+generation; the per-layer KV
    cache is [n_slots, n_kv_heads, max_seq_len, head_dim].
    """

    def __init__(self, model, params, config: EngineConfig = EngineConfig()):
        self.model = model
        self.params = params
        # Buckets beyond the cache length can never be inserted; drop
        # them so submit() rejects oversized prompts up front instead of
        # crashing the loop thread at dynamic_update_slice time.
        max_len = model.cfg.max_seq_len
        buckets = tuple(b for b in config.prefill_buckets if b <= max_len)
        if not buckets:
            buckets = (max_len,)
        config = dataclasses.replace(config, prefill_buckets=buckets)
        self.cfg = config
        self._rng = jax.random.PRNGKey(config.seed)
        self._prefill_q: 'queue.Queue[Request]' = queue.Queue()
        # Orders submit()'s error-check-then-enqueue against the crash
        # path's set-error-then-drain: without it a request enqueued
        # between those two drain steps is never failed and its tokens()
        # blocks forever.
        self._submit_lock = threading.Lock()
        self._slots: List[Optional[_Slot]] = [None] * config.n_slots
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.error: Optional[BaseException] = None
        self._build_fns()
        self._init_cache()

    @property
    def healthy(self) -> bool:
        return self.error is None

    # ----- jitted compute ----------------------------------------------------
    def _build_fns(self):
        model, temp = self.model, self.cfg.temperature

        def sample(logits, rng):                     # logits [..., V] f32
            if temp > 0.0:
                return jax.random.categorical(rng, logits / temp, axis=-1)
            return jnp.argmax(logits, axis=-1)

        def prefill_insert(params, big_cache, last_toks, lens, tokens,
                           length, slot, rng):
            """Fused prefill + slot insert, one dispatch, nothing synced.
            tokens [1, P(bucket)]."""
            positions = jnp.arange(tokens.shape[1])[None, :]
            logits, cache = model.apply(
                {'params': params}, tokens, positions=positions,
                decode=True, mutable=['cache'])
            last = jax.lax.dynamic_index_in_dim(logits, length - 1, axis=1,
                                                keepdims=False)  # [1, V]
            first = sample(last, rng)[0]                          # scalar

            def _ins(big, small):
                idx = (slot,) + (0,) * (big.ndim - 1)
                return jax.lax.dynamic_update_slice(big, small, idx)

            big_cache = jax.tree_util.tree_map(_ins, big_cache,
                                               cache['cache'])
            return (big_cache, last_toks.at[slot].set(first),
                    lens.at[slot].set(length))

        steps = self.cfg.steps_per_call
        max_len = model.cfg.max_seq_len

        def decode(params, cache, last_tokens, lengths, rng):
            """`steps` tokens for every slot in one dispatch.  Returns
            out [steps+1, n_slots] (row 0 = the incoming last tokens, so
            freshly admitted slots' first tokens ride the same fetch)."""
            def body(carry, rng_t):
                cache, last, lens = carry
                # Clamp writes for slots running past the cap: confined
                # to slots being retired (their cache is re-inserted).
                positions = jnp.minimum(lens, max_len - 1)[:, None]
                logits, new_cache = model.apply(
                    {'params': params, 'cache': cache},
                    last[:, None], positions=positions,
                    decode=True, mutable=['cache'])
                nxt = sample(logits[:, 0, :], rng_t)         # [B]
                return (new_cache['cache'], nxt, lens + 1), nxt

            (cache, last, lens), toks = jax.lax.scan(
                body, (cache, last_tokens, lengths),
                jax.random.split(rng, steps))
            out = jnp.concatenate([last_tokens[None, :], toks], axis=0)
            return out, cache, last, lens                    # [T+1, B]

        self._prefill_insert = jax.jit(prefill_insert,
                                       donate_argnums=(1, 2, 3))
        self._decode = jax.jit(decode, donate_argnums=(1, 2, 3))

    def _init_cache(self):
        """Materialize the big cache by tracing a dummy decode batch."""
        n = self.cfg.n_slots
        tokens = jnp.zeros((n, 1), jnp.int32)
        positions = jnp.zeros((n, 1), jnp.int32)
        _, cache = self.model.apply(
            {'params': self.params}, tokens, positions=positions,
            decode=True, mutable=['cache'])
        self._cache = cache['cache']
        # Device-resident engine state: synced host-ward once per step.
        self._last_d = jnp.zeros((n,), jnp.int32)
        self._lens_d = jnp.zeros((n,), jnp.int32)

    # ----- public API --------------------------------------------------------
    def submit(self, prompt_ids: List[int],
               max_new_tokens: int = 64) -> Request:
        max_prompt = self.cfg.prefill_buckets[-1]
        limit = self.model.cfg.max_seq_len
        if len(prompt_ids) > max_prompt or len(prompt_ids) >= limit:
            raise ValueError(
                f'prompt len {len(prompt_ids)} exceeds the largest '
                f'prefill bucket {max_prompt} (cache length {limit})')
        if len(prompt_ids) + max_new_tokens > limit:
            max_new_tokens = limit - len(prompt_ids)
        req = Request(list(prompt_ids), max_new_tokens)
        with self._submit_lock:
            if self.error is not None:
                raise RuntimeError(
                    f'decode engine is dead: {self.error!r}')
            self._prefill_q.put(req)
        return req

    def generate(self, prompt_ids: List[int],
                 max_new_tokens: int = 64) -> List[int]:
        """Synchronous helper: submit and wait."""
        return self.submit(prompt_ids, max_new_tokens).tokens()

    def start(self):
        self._thread = threading.Thread(target=self._loop,
                                        name='decode-engine', daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    # ----- engine loop -------------------------------------------------------
    def _bucket(self, n: int) -> int:
        for b in self.cfg.prefill_buckets:
            if n <= b:
                return b
        raise ValueError(f'prompt len {n} exceeds buckets')

    def _next_rng(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _admit(self, slot_id: int, req: Request) -> None:
        """Dispatch prefill+insert; does NOT sync — the first token is
        emitted from row 0 of the next decode call's output."""
        plen = len(req.prompt_ids)
        bucket = self._bucket(plen)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :plen] = req.prompt_ids
        self._cache, self._last_d, self._lens_d = self._prefill_insert(
            self.params, self._cache, self._last_d, self._lens_d,
            jnp.asarray(padded), plen, jnp.asarray(slot_id),
            self._next_rng())
        self._slots[slot_id] = _Slot(req, plen)

    def _emit(self, req: Request, tok: int) -> None:
        req.emitted += 1
        req.out.put(tok)

    def _finished(self, slot: _Slot, tok: int) -> bool:
        return (tok == self.cfg.eos_id or
                slot.request.emitted >= slot.request.max_new_tokens)

    def _retire(self, slot_id: int) -> None:
        slot = self._slots[slot_id]
        slot.request.finished_at = time.perf_counter()
        slot.request.out.put(None)
        self._slots[slot_id] = None

    def step(self) -> int:
        """One engine iteration (admit + decode).  Returns #active slots.
        Exposed for tests and for single-threaded benchmarking."""
        for i in range(self.cfg.n_slots):
            if self._slots[i] is None and not self._prefill_q.empty():
                try:
                    req = self._prefill_q.get_nowait()
                except queue.Empty:
                    break
                self._admit(i, req)
        active = [i for i in range(self.cfg.n_slots)
                  if self._slots[i] is not None]
        if not active:
            return 0
        out, self._cache, self._last_d, self._lens_d = self._decode(
            self.params, self._cache, self._last_d, self._lens_d,
            self._next_rng())
        out = np.asarray(out)            # [T+1, B] — the ONE sync per step
        now = time.perf_counter()
        for i in active:
            slot = self._slots[i]
            start = 0
            if slot.first_pending:
                slot.first_pending = False
                slot.request.first_token_at = now
            else:
                start = 1                # row 0 was emitted last step
            for t in range(start, out.shape[0]):
                tok = int(out[t, i])
                slot.length += 1
                self._emit(slot.request, tok)
                if self._finished(slot, tok):
                    self._retire(i)
                    break                # rest of this call's tokens: waste
        return len(active)

    def _loop(self):
        while not self._stop.is_set():
            try:
                n = self.step()
            except BaseException as e:  # pylint: disable=broad-except
                # A dead loop thread must not strand callers: fail every
                # in-flight and queued request, flip unhealthy (the HTTP
                # server's /health reports it, so serve's readiness
                # probes replace this replica).
                logger.exception('decode engine loop crashed')
                with self._submit_lock:
                    self.error = e
                    for i, slot in enumerate(self._slots):
                        if slot is not None:
                            slot.request.finished_at = time.perf_counter()
                            slot.request.out.put(None)
                            self._slots[i] = None
                    while True:
                        try:
                            req = self._prefill_q.get_nowait()
                        except queue.Empty:
                            break
                        req.finished_at = time.perf_counter()
                        req.out.put(None)
                return
            if n == 0:
                time.sleep(0.001)
