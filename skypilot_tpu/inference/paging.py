"""Host-side bookkeeping for the paged KV cache: page allocator +
radix prefix cache.

The engine's per-layer KV pool is ``[n_pages, n_kv_heads, page_size,
head_dim]`` on device; everything in this module is pure-Python loop-
thread state describing who owns which page.  Nothing here ever touches
a device array — page-table updates are host-side by design (the
``skytpu check`` one-sync-per-step contract), and the jitted gathers /
scatters in inference/engine.py consume the tables this module builds.

Ownership model (the invariant tests/test_serve_paged.py soaks):

- every page's refcount = (number of live slots whose page table
  references it) + (1 if a radix-cache node holds it);
- a page referenced by two live slots is ALWAYS a shared prefix page
  (both slots matched it through the radix cache) — slots never share
  the pages they write;
- pages are immutable once full: a prefix extension allocates fresh
  pages ("copy-on-extend" at page granularity degenerates to
  plain extension because matches are page-aligned and writes only
  land at positions past the match);
- freed-page count is conserved: free + referenced == n_pages - 1
  (page 0 is the trash page inactive slots scribble into).

Dtype-blindness: with ``kv_dtype='int8'`` the device pool becomes a
QuantPages pair — int8 payload of the same ``[n_pages, n_kv_heads,
page_size, head_dim]`` geometry plus a per-(page, head, position) f32
scale (inference/kv_quant.py) — but page IDENTITY is unchanged, so
nothing in this module knows or cares: the allocator, radix cache, and
refcount invariants operate on page indices, and the same page table
drives the quantized gather/scatter.  Keep it that way — a dtype
branch here would couple host bookkeeping to device layout.
"""
from __future__ import annotations

import zlib
from typing import Dict, List, Optional, Tuple

# Page 0 is reserved as the TRASH page: every page-table entry beyond a
# slot's reservation points here, so clamped/overrun device writes land
# somewhere harmless (never read at an unmasked position).
TRASH_PAGE = 0


class PagePool:
    """Free-list page allocator with refcounts (host loop thread only).

    Deterministic on purpose (LIFO free list, no clocks): two identical
    runs produce identical page tables, which keeps the engine's
    bit-identical-rerun tests meaningful with paging on.
    """

    def __init__(self, n_pages: int, page_size: int) -> None:
        if n_pages < 2:
            raise ValueError(
                f'kv page pool needs >= 2 pages (1 trash + 1 usable), '
                f'got {n_pages}')
        self.n_pages = n_pages
        self.page_size = page_size
        # LIFO stack of free page ids (1..n_pages-1; 0 is trash).
        self._free: List[int] = list(range(n_pages - 1, 0, -1))
        self._refs: List[int] = [0] * n_pages

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return (self.n_pages - 1) - len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Allocate ``n`` pages (refcount 1 each) or None — never a
        partial allocation (admission is all-or-nothing so a half-
        reserved request cannot deadlock the pool)."""
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._refs[p] = 1
        return pages

    def ref(self, pages: List[int]) -> None:
        for p in pages:
            assert self._refs[p] > 0, f'ref of free page {p}'
            self._refs[p] += 1

    def release(self, pages: List[int]) -> int:
        """Drop one reference per page; pages reaching zero return to
        the free list.  Returns how many were freed."""
        freed = 0
        for p in pages:
            assert self._refs[p] > 0, f'release of free page {p}'
            self._refs[p] -= 1
            if self._refs[p] == 0:
                self._free.append(p)
                freed += 1
        return freed

    def refcount(self, page: int) -> int:
        return self._refs[page]

    def check_conserved(self) -> None:
        """Soak-test invariant: every non-trash page is either free or
        referenced, never both, and the counts add up."""
        free_set = set(self._free)
        assert len(free_set) == len(self._free), 'double-free'
        for p in range(1, self.n_pages):
            in_free = p in free_set
            assert (self._refs[p] == 0) == in_free, (
                f'page {p}: refs={self._refs[p]} free={in_free}')
        assert self.free_pages + self.used_pages == self.n_pages - 1


class _Node:
    __slots__ = ('key', 'page', 'children', 'parent', 'last_hit',
                 'digest')

    def __init__(self, key: Optional[Tuple[int, ...]], page: int,
                 parent: Optional['_Node']) -> None:
        self.key = key
        self.page = page
        self.children: Dict[Tuple[int, ...], '_Node'] = {}
        self.parent = parent
        self.last_hit = 0
        # Path digest: folds the parent's digest with this node's
        # token key, so the digest identifies the full PREFIX the node
        # spells, not just its last page.  Content-only (no pool page
        # ids, no clocks): two caches holding the same prefixes agree
        # byte-for-byte across processes.
        if parent is None:
            self.digest = 0
        else:
            self.digest = zlib.crc32(
                repr((parent.digest, key)).encode('ascii'))


class RadixCache:
    """Radix/prefix cache over the page pool, keyed on exact token
    content at page granularity.

    One trie node per cached page; a node's path from the root spells
    the token prefix whose KV the page holds.  Exact token tuples (not
    hashes) key the children map — Python hashes them under the hood
    and collisions can never alias two different prefixes.  LRU is a
    deterministic logical clock bumped on every match, so eviction
    order is reproducible in tests.
    """

    def __init__(self, pool: PagePool) -> None:
        self._pool = pool
        self._root = _Node(None, TRASH_PAGE, None)
        self._clock = 0
        self.nodes = 0
        # Rolling fingerprint of the RESIDENT prefix set: XOR of every
        # live node's path digest, updated O(1) on insert/evict.  Equal
        # caches expose equal fingerprints (XOR is order-free), so the
        # federated `skytpu_engine_prefix_fingerprint` gauge tells the
        # router which replicas hold the same hot prefixes.
        self.fingerprint = 0

    def _keys(self, tokens: List[int], n_pages: int):
        ps = self._pool.page_size
        for i in range(n_pages):
            yield tuple(tokens[i * ps:(i + 1) * ps])

    def match(self, tokens: List[int],
              max_pages: int) -> Tuple[int, List[int]]:
        """Longest cached page-aligned prefix of ``tokens`` (at most
        ``max_pages`` pages).  Takes one pool reference per matched
        page ON BEHALF OF THE CALLER — the matching slot releases them
        at retire exactly like the pages it owns."""
        self._clock += 1
        node, pages = self._root, []
        for key in self._keys(tokens, max_pages):
            child = node.children.get(key)
            if child is None:
                break
            child.last_hit = self._clock
            pages.append(child.page)
            node = child
        if pages:
            self._pool.ref(pages)
        return len(pages), pages

    def insert(self, tokens: List[int], pages: List[int]) -> int:
        """Record ``pages[i]`` as holding the KV of tokens
        ``[i*ps, (i+1)*ps)``.  Walks the trie, adding nodes only where
        missing (an existing node keeps ITS page — the caller's
        duplicate page is simply not adopted and frees at retire).
        Each adopted page gains one trie reference.  Returns the number
        of pages adopted."""
        self._clock += 1
        node, adopted = self._root, 0
        for i, key in enumerate(self._keys(tokens, len(pages))):
            child = node.children.get(key)
            if child is None:
                child = _Node(key, pages[i], node)
                node.children[key] = child
                self._pool.ref([pages[i]])
                self.nodes += 1
                self.fingerprint ^= child.digest
                adopted += 1
            child.last_hit = self._clock
            node = child
        return adopted

    def _evictable_leaves(self) -> List[_Node]:
        out, stack = [], list(self._root.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            elif self._pool.refcount(n.page) == 1:
                # Only the trie holds it: no live slot, safe to drop.
                out.append(n)
        return out

    def evict(self, n_pages: int) -> int:
        """LRU-evict up to ``n_pages`` cached pages (leaf nodes whose
        page no live slot references; evicting a leaf may expose its
        parent as the next candidate).  Returns pages actually freed.
        O(nodes) per eviction — fine at serving scale where evictions
        are rare; a heap is the upgrade path if they stop being."""
        freed = 0
        while freed < n_pages:
            leaves = self._evictable_leaves()
            if not leaves:
                break
            victim = min(leaves, key=lambda nd: (nd.last_hit, nd.page))
            del victim.parent.children[victim.key]
            self.nodes -= 1
            self.fingerprint ^= victim.digest
            freed += self._pool.release([victim.page])
        return freed
