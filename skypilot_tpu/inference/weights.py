"""Checkpoint loading for serve replicas.

The reference's serving story is convert-a-trained-checkpoint-then-serve
(/root/reference/examples/tpu/v6e/README.md:100-118: convert Llama
weights into a bucket, point the JetStream server at it).  Here the
equivalent is: a training run checkpoints via orbax
(train/checkpoint.py), and the serve replica restores the params at
startup — from a local directory or straight from a `gs://` bucket.

No conversion step is needed: train and serve share the same Flax
parameter tree, and orbax restores onto whatever topology the replica
has (single chip or a sharded mesh).
"""
from __future__ import annotations

import os
import tempfile
from typing import Any, Optional

import jax

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)


def _materialize_local(path: str) -> str:
    """Return a local directory holding the checkpoint.

    `gs://bucket/prefix` paths are synced down to a temp dir first
    (gsutil, or the fake-GCS root under tests — data/storage.py).
    Local paths are returned as-is.
    """
    if path.startswith('gs://'):
        from skypilot_tpu.data import storage as storage_lib
        rest = path[len('gs://'):]
        bucket, _, prefix = rest.partition('/')
        local = tempfile.mkdtemp(prefix='skytpu-ckpt-')
        logger.info(f'fetching checkpoint {path} -> {local}')
        storage_lib.GcsStore(bucket).sync_down(local, prefix)
        return local
    return os.path.abspath(os.path.expanduser(path))


def _cleanup_fetched(path: str, local: str) -> None:
    """Remove the temp download for gs:// restores (a crash-looping
    replica must not fill /tmp with multi-GB checkpoint copies)."""
    if local != os.path.abspath(os.path.expanduser(path)):
        import shutil
        shutil.rmtree(local, ignore_errors=True)


def serving_shardings(model, mesh, rules: Optional[Any] = None) -> Any:
    """Per-leaf NamedShardings for a serve replica's param tree.

    Derived from the model's logical-axis annotations exactly like the
    trainer does (`nn.logical_to_mesh_sharding` over an abstract init),
    so train and serve agree on what shards where; the serving defaults
    put attention heads / MLP hidden / vocab on the `tensor` axis and
    replicate the rest (parallel/sharding.py DEFAULT_RULES with every
    non-tensor axis sized 1 on a serve mesh).  Any dimension the mesh
    does not divide evenly falls back to replicated for that axis — a
    vocab or ffn size that does not split cleanly must not refuse to
    serve.  Returns an UNBOXED tree aligned with the raw param arrays.
    """
    import math

    import flax.linen as nn
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from skypilot_tpu.parallel import sharding as sharding_lib

    rules = list(rules or sharding_lib.DEFAULT_RULES)
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32)))
    specs = nn.get_partition_spec(abstract)['params']
    shardings = nn.meta.unbox(
        nn.logical_to_mesh_sharding(specs, mesh, rules))
    leaves_abs = nn.meta.unbox(abstract['params'])

    def _guard(sharding, leaf):
        spec = sharding.spec
        kept = []
        for i, axes in enumerate(spec):
            if axes is None:
                kept.append(None)
                continue
            names = (axes,) if isinstance(axes, str) else tuple(axes)
            size = math.prod(mesh.shape[a] for a in names)
            kept.append(axes if leaf.shape[i] % size == 0 else None)
        return NamedSharding(mesh, PartitionSpec(*kept))

    return jax.tree.map(_guard, shardings, leaves_abs)


def load_serving_params(path: str, step: Optional[int] = None,
                        dtype: Any = None, shardings: Any = None) -> Any:
    """Restore model params from an orbax checkpoint directory.

    Accepts either a params-only checkpoint or a full TrainState
    checkpoint (train/trainer.py saves the latter); for a TrainState the
    optimizer state is discarded — serving only needs `params`.

    The restore is *topology-independent*: a checkpoint written on an
    8-chip training mesh restores onto a single-chip serve replica (or
    any other device set).  Orbax's default restore re-applies the
    *saved* shardings and hard-fails when the saved device mesh differs
    from the replica's — precisely the production case (train sharded,
    serve single-chip) — so every leaf is restored to host numpy via
    per-leaf RestoreArgs and the params are then device_put, optionally
    cast to `dtype` (pass jnp.bfloat16 to halve HBM for big models).

    `shardings` (a tree of NamedShardings matching the param tree, e.g.
    from `serving_shardings`) places each leaf DIRECTLY onto its mesh
    layout as it is restored: a tensor-parallel replica never
    materializes the full tree on any single device — the property that
    lets a 70B checkpoint load onto chips that individually cannot hold
    it.
    """
    import numpy as np
    import orbax.checkpoint as ocp

    local = _materialize_local(path)
    try:
        mgr = ocp.CheckpointManager(local)
        if step is None:
            step = mgr.latest_step()
        mgr.close()
        if step is None:
            raise FileNotFoundError(
                f'no checkpoint steps found under {path!r} '
                f'(resolved to {local!r})')
        logger.info(f'restoring checkpoint step {step} from {path}')
        step_dir = os.path.join(local, str(step), 'default')
        ckptr = ocp.PyTreeCheckpointer()
        meta = ckptr.metadata(step_dir)
        if hasattr(meta, 'item_metadata'):
            # Newer orbax wraps the tree in CheckpointMetadata; older
            # (<=0.7) returns the metadata tree directly.
            meta = meta.item_metadata.tree
        is_leaf = lambda x: hasattr(x, 'dtype') and hasattr(x, 'shape')  # noqa: E731,E501
        restore_args = jax.tree.map(
            lambda _: ocp.RestoreArgs(restore_type=np.ndarray), meta,
            is_leaf=is_leaf)
        restored = ckptr.restore(
            step_dir,
            args=ocp.args.PyTreeRestore(restore_args=restore_args))
    finally:
        _cleanup_fetched(path, local)
    # TrainState layout: {'params': ..., 'opt_state': ..., 'step': ...}
    if isinstance(restored, dict) and 'params' in restored:
        restored = restored['params']

    def _put(x, sharding=None):
        if dtype is not None and jax.numpy.issubdtype(x.dtype,
                                                      jax.numpy.floating):
            x = x.astype(dtype)
        return jax.device_put(x, sharding)

    if shardings is not None:
        return jax.tree.map(_put, restored, shardings)
    return jax.tree.map(_put, restored)
