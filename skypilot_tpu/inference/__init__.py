"""TPU-native serving engine (JetStream twin).

The reference serves LLMs by launching third-party engines (JetStream,
vLLM) from recipe YAMLs (examples/tpu/v6e/serve-llama2-7b.yaml,
llm/vllm/serve.yaml); here the engine is first-party: a continuous-
batching decode loop over the models' KV caches, plus an HTTP completions
server that slots into `serve` as the replica workload.
"""
from skypilot_tpu.inference.engine import DecodeEngine, EngineConfig

__all__ = ['DecodeEngine', 'EngineConfig']
