"""KV-page handoff between prefill and decode replicas (disaggregated
serving, ThunderServe arXiv:2502.09334).

A prefill replica runs the ordinary chunked/fused prefill into its
paged KV pool (PR 12 made pages the transferable unit), then exports
the request's pages plus the sampled first token as ONE compact binary
payload; the decode replica adopts the pages into its own pool at page
granularity — no per-token recompute — and continues decoding.  This
module owns the wire format and the bounded-timeout HTTP push; it is
deliberately jax-free (pure numpy + stdlib) so the serve LB can import
its header constants without dragging in a device runtime.

Wire format (version 1, little-endian):

    MAGIC 'SKVT1' | u32 header_len | header JSON (utf-8) | page data

The header carries dtype/shape per cache leaf, the page geometry, the
prompt ids, the sampled first token and a CRC32 of the page data —
a truncated or corrupted transfer fails loudly at parse time instead
of decoding garbage.  Page data is LAYER-MAJOR: all of leaf 0's pages
(``[n_pages, heads, page_size, head_dim]``, C-contiguous), then leaf
1's, matching ``jax.tree.leaves`` order of the engine's cache tree —
both engines run the same model so the leaf order is identical by
construction (and the leaf count/shapes are checked at adopt).

Push/pull: the serve LB stamps ``X-Skytpu-Decode-Url`` (one or more
candidate decode replicas, comma-separated, ranked by its routing
policy) on the request it proxies to the prefill pool; the prefill
replica POSTs the payload to ``/v1/kv_adopt`` on the first candidate
that accepts, with a hard client timeout — a dead decode replica fails
the push in bounded time and the NEXT candidate gets the same payload
(re-route, no re-prefill).  Transfer outcomes land in the
``skytpu_lb_kv_transfer_*`` families, federated like every other
serve metric.
"""
from __future__ import annotations

import dataclasses
import json
import time
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from skypilot_tpu import sky_logging
from skypilot_tpu.server import metrics as metrics_lib

logger = sky_logging.init_logger(__name__)

_MAGIC = b'SKVT1'
VERSION = 1

# Stamped by the serve LB on requests proxied to the PREFILL pool: the
# decode replicas (comma-separated URLs, in routing-policy preference
# order) the prefill replica should push this request's KV pages to.
DECODE_URL_HEADER = 'X-Skytpu-Decode-Url'
# Route the decode replica accepts handoff payloads on.
ADOPT_ROUTE = '/v1/kv_adopt'

# Hard deadline for one handoff push (connect + upload + the decode
# replica's FULL generation, since the adopt response carries the
# completion).  Generous — streaming decodes legitimately run long —
# but finite: a wedged decode replica must fail the push so the next
# candidate (or the local monolithic fallback) gets the request.
DEFAULT_PUSH_TIMEOUT_SECONDS = 300.0
# The transfer itself (connect + request write) gets a much tighter
# bound: payloads are MBs, not streams, and a transfer that cannot
# start quickly should fail over to the next candidate.
DEFAULT_CONNECT_TIMEOUT_SECONDS = 10.0


@dataclasses.dataclass
class KVHandoff:
    """One request's transferable prefill state."""
    prompt_ids: List[int]
    first_token: int
    max_new_tokens: int
    page_size: int
    # Per cache leaf: [n_kv_pages, heads, page_size, head_dim] numpy,
    # jax.tree.leaves order.
    leaves: List[np.ndarray]
    request_id: Optional[str] = None

    @property
    def n_kv_pages(self) -> int:
        return self.leaves[0].shape[0] if self.leaves else 0


def serialize(handoff: KVHandoff) -> bytes:
    """KVHandoff -> one self-describing binary payload."""
    blobs = []
    leaf_meta = []
    for leaf in handoff.leaves:
        arr = np.ascontiguousarray(leaf)
        blobs.append(arr.tobytes())
        leaf_meta.append({'shape': list(arr.shape),
                          'dtype': arr.dtype.name})
    data = b''.join(blobs)
    header = {
        'version': VERSION,
        'prompt_ids': list(map(int, handoff.prompt_ids)),
        'first_token': int(handoff.first_token),
        'max_new_tokens': int(handoff.max_new_tokens),
        'page_size': int(handoff.page_size),
        'request_id': handoff.request_id,
        'leaves': leaf_meta,
        'data_bytes': len(data),
        'crc32': zlib.crc32(data) & 0xffffffff,
    }
    hdr = json.dumps(header, separators=(',', ':')).encode('utf-8')
    return b''.join([_MAGIC, len(hdr).to_bytes(4, 'little'), hdr, data])


def deserialize(payload: bytes) -> KVHandoff:
    """Parse + integrity-check one payload; raises ValueError on any
    corruption (magic, truncation, checksum, shape mismatch) — a bad
    transfer must never scatter garbage into a live KV pool."""
    if len(payload) < len(_MAGIC) + 4 or \
            payload[:len(_MAGIC)] != _MAGIC:
        raise ValueError('kv-handoff payload: bad magic')
    off = len(_MAGIC)
    hdr_len = int.from_bytes(payload[off:off + 4], 'little')
    off += 4
    if len(payload) < off + hdr_len:
        raise ValueError('kv-handoff payload: truncated header')
    try:
        header = json.loads(payload[off:off + hdr_len].decode('utf-8'))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ValueError(f'kv-handoff payload: unparseable header: {e}')
    if header.get('version') != VERSION:
        raise ValueError(f'kv-handoff payload: version '
                         f'{header.get("version")} != {VERSION}')
    off += hdr_len
    data = payload[off:]
    if len(data) != header['data_bytes']:
        raise ValueError(
            f'kv-handoff payload: data truncated '
            f'({len(data)} of {header["data_bytes"]} bytes)')
    if (zlib.crc32(data) & 0xffffffff) != header['crc32']:
        raise ValueError('kv-handoff payload: checksum mismatch')
    leaves = []
    pos = 0
    for meta in header['leaves']:
        shape = tuple(meta['shape'])
        dtype = np.dtype(meta['dtype'])
        n = int(np.prod(shape)) * dtype.itemsize
        leaves.append(np.frombuffer(
            data, dtype=dtype, count=int(np.prod(shape)),
            offset=pos).reshape(shape))
        pos += n
    if pos != len(data):
        raise ValueError('kv-handoff payload: leaf sizes do not cover '
                         'the data section')
    return KVHandoff(prompt_ids=header['prompt_ids'],
                     first_token=header['first_token'],
                     max_new_tokens=header['max_new_tokens'],
                     page_size=header['page_size'],
                     leaves=leaves,
                     request_id=header.get('request_id'))


def parse_decode_targets(header_value: Optional[str]) -> List[str]:
    """The LB's decode-candidate header -> ordered URL list."""
    if not header_value:
        return []
    return [u.strip() for u in header_value.split(',') if u.strip()]


async def push(session, decode_urls: Sequence[str], payload: bytes,
               request_id: Optional[str] = None,
               timeout_s: float = DEFAULT_PUSH_TIMEOUT_SECONDS,
               ) -> Tuple[Optional[Dict], Optional[str]]:
    """Push one payload to the first decode replica that takes it.

    Tries ``decode_urls`` in order (the LB ranked them); a candidate
    that fails — connect refused, timeout, non-200 — is skipped and the
    SAME payload goes to the next one: re-routing an in-flight handoff
    costs one RPC, never a re-prefill.  Returns (decode replica's JSON
    completion, winning URL), or (None, None) when every candidate
    failed (the caller falls back to monolithic serving).
    """
    import aiohttp
    headers = {'Content-Type': 'application/octet-stream'}
    if request_id:
        from skypilot_tpu.server import tracing
        headers[tracing.TRACE_HEADER] = request_id
    for url in decode_urls:
        t0 = time.perf_counter()
        outcome = 'error'
        try:
            async with session.post(
                    url.rstrip('/') + ADOPT_ROUTE, data=payload,
                    headers=headers,
                    timeout=aiohttp.ClientTimeout(
                        total=timeout_s,
                        sock_connect=DEFAULT_CONNECT_TIMEOUT_SECONDS,
                    )) as resp:
                if resp.status == 200:
                    body = await resp.json()
                    outcome = 'ok'
                    metrics_lib.inc_counter(
                        'skytpu_lb_kv_transfer_bytes_total',
                        float(len(payload)))
                    return body, url
                logger.warning(
                    f'kv handoff to {url} rejected: {resp.status}')
        except Exception as e:  # pylint: disable=broad-except
            # aiohttp client errors, timeouts, DNS — all mean "this
            # candidate is out"; the next one gets the payload.
            logger.warning(f'kv handoff to {url} failed: {e}')
        finally:
            metrics_lib.inc_counter('skytpu_lb_kv_transfer_total',
                                    outcome=outcome)
            metrics_lib.observe_hist(
                'skytpu_lb_kv_transfer_seconds',
                time.perf_counter() - t0, outcome=outcome)
    return None, None
