"""HTTP completions server over the decode engine.

The serve replica workload (analog of the reference's JetStream server
launched by examples/tpu/v6e/serve-llama2-7b.yaml).  Routes:

- GET  /health        -> 200 once the engine thread is up (readiness
                         probes from serve's replica manager hit this).
- GET  /metrics       -> Prometheus exposition: engine TTFT /
                         inter-token-latency histograms, token counters,
                         occupancy/queue gauges.  The serve load
                         balancer scrapes this per replica and federates
                         the series under a replica="<id>" label.
- POST /v1/completions  {"prompt": "...", "max_tokens": N} or
                        {"prompt_ids": [...], "max_tokens": N}
                        -> {"ids": [...], "text": "...", "usage": {...}}
                        Prompts longer than the largest prefill bucket
                        are admitted via chunked prefill (up to
                        max_prompt_len, default max_seq_len - 1); a
                        prompt beyond that limit gets 413 with the
                        limit in the body.
- POST /v1/kv_adopt     Disaggregated serving: a prefill replica's
                        KV-handoff payload (inference/kv_transfer.py
                        binary format).  The engine adopts the pages
                        into its own pool and decodes; the response is
                        the SAME completion JSON /v1/completions
                        returns, so the prefill replica can relay it
                        verbatim.

Roles (`--role`, env SKYTPU_SERVE_ROLE): `monolithic` (default) serves
each request end to end.  A `prefill` replica, when the serve LB
stamps X-Skytpu-Decode-Url with decode-pool candidates, runs only the
prefill phase and PUSHES the paged KV + sampled first token to the
first candidate that accepts (bounded timeout; a dead candidate fails
over to the next — the payload is re-routed, never re-prefilled).  If
every candidate fails it falls back to serving monolithically, and the
re-prefill hits its own prefix cache (the prompt pages were donated at
export).  A `decode` replica accepts /v1/kv_adopt.  Both roles run the
full engine, so a mis-routed request still completes.
- GET  /debug/requests        -> flight-recorder summaries (recent
                         request ids + their span names).
- GET  /debug/requests/<id>   -> one request's span events + TTFT
                         decomposition (`?format=chrome` exports the
                         Chrome-trace/Perfetto document).  This is what
                         `skytpu trace <id>` renders.

Every response carries `X-Skytpu-Queued-Prefill-Tokens` (the engine's
queued-prefill-token backlog — same value as the gauge): the serve LB
reads it for free on the proxy path and feeds queue-aware admission
control and least_load routing.  Every response also carries
`X-Skytpu-Request-Id` — honored from the request when the client (or
the serve LB, which mints one at admission) sent it, minted here
otherwise — and the id keys the request's span events in the always-on
flight recorder (server/tracing.py; ring size via
SKYTPU_TRACE_RING_SIZE).

Text prompts use a byte-level tokenizer (token id = byte value), which is
model-agnostic and dependency-free; real deployments pass `prompt_ids`
from their own tokenizer.
"""
from __future__ import annotations

import argparse
import asyncio
import os
from typing import List

import aiohttp
from aiohttp import web

from skypilot_tpu import sky_logging
from skypilot_tpu.inference import kv_transfer
from skypilot_tpu.inference.engine import DecodeEngine, EngineConfig
from skypilot_tpu.perf import profiler as profiler_lib
from skypilot_tpu.server import metrics as metrics_lib
from skypilot_tpu.server import tracing

logger = sky_logging.init_logger(__name__)


def encode_bytes(text: str) -> List[int]:
    return list(text.encode('utf-8'))


def decode_bytes(ids: List[int]) -> str:
    return bytes(i for i in ids if 0 <= i < 256).decode('utf-8',
                                                        errors='replace')


# Engine backlog stamped on every response: queued prefill tokens.  The
# serve load balancer reads it for free on the proxy response path and
# feeds queue-aware admission control + least_load routing (shared
# constant: server/metrics.py owns the cross-process names).
BACKLOG_HEADER = metrics_lib.BACKLOG_HEADER


def build_app(engine: DecodeEngine,
              role: str = 'monolithic') -> web.Application:
    # One pooled client session for KV-handoff pushes, created lazily
    # on the app's own event loop and closed with the app.
    _state = {'session': None}

    def _session() -> aiohttp.ClientSession:
        if _state['session'] is None or _state['session'].closed:
            _state['session'] = aiohttp.ClientSession()
        return _state['session']

    async def _close_session(_app):
        if _state['session'] is not None and not _state['session'].closed:
            await _state['session'].close()

    @web.middleware
    async def stamp_backlog(request: web.Request, handler):
        # Honor the caller's request id (the serve LB mints one at
        # admission) or mint one here, so every request is traceable
        # even library-direct; stamped on the response so the client
        # always learns the id to `skytpu trace`.
        rid = request.headers.get(tracing.TRACE_HEADER) or \
            tracing.mint_request_id()
        request['skytpu_request_id'] = rid
        resp = await handler(request)
        resp.headers[BACKLOG_HEADER] = str(engine.queued_prefill_tokens)
        resp.headers[tracing.TRACE_HEADER] = rid
        return resp

    # aiohttp's default client_max_size is 1 MiB — a KV-handoff
    # payload (layer-major pages of a real model) is tens to hundreds
    # of MB, so the default would 413 every /v1/kv_adopt and silently
    # degrade disaggregation to permanent monolithic fallback.
    max_payload = int(os.environ.get('SKYTPU_SERVE_MAX_PAYLOAD_BYTES',
                                     str(2 * 1024 ** 3)))
    app = web.Application(middlewares=[stamp_backlog],
                          client_max_size=max_payload)

    async def health(_request):
        if not engine.healthy:
            return web.json_response(
                {'status': 'error', 'error': repr(engine.error),
                 'role': role}, status=503)
        return web.json_response({'status': 'ok', 'role': role})

    def _completion_json(rid, ids, out, req):
        return {
            'ids': out,
            'text': decode_bytes(out),
            'request_id': rid,
            'usage': {
                'prompt_tokens': len(ids),
                'completion_tokens': len(out),
                'ttft_ms': round(
                    (req.first_token_at - req.submitted_at) * 1e3, 2)
                if req.first_token_at else None,
            },
        }

    async def _serve_monolithic(ids, max_tokens, rid):
        try:
            req = engine.submit(ids, max_tokens, request_id=rid)
        except ValueError as e:
            # Admission rejection: the prompt exceeds max_prompt_len
            # (engine message carries the limit).  413, not 400 — the
            # request was well-formed, just too large; clients can read
            # the limit and re-chunk.
            tracing.record_instant(rid, 'server.reject', status=413,
                                   prompt_tokens=len(ids),
                                   max_prompt_len=engine.max_prompt_len)
            return web.json_response(
                {'error': str(e),
                 'max_prompt_len': engine.max_prompt_len}, status=413)
        out = await asyncio.get_event_loop().run_in_executor(
            None, req.tokens)
        return web.json_response(_completion_json(rid, ids, out, req))

    def _export_payload(req, ids, max_tokens, rid):
        """Executor-thread half of a handoff: the device->host copy of
        the gathered pages plus serialization — never on the event
        loop, never on the engine loop."""
        exported = engine.export_result(req)
        return kv_transfer.serialize(kv_transfer.KVHandoff(
            prompt_ids=ids,
            first_token=exported['first_token'],
            max_new_tokens=max_tokens,
            page_size=engine.cfg.kv_page_size,
            leaves=exported['leaves'],
            request_id=rid))

    async def _serve_prefill_handoff(ids, max_tokens, rid, targets):
        """Prefill role: run the prefill phase locally, push the KV
        pages + first token to a decode candidate, relay its
        completion.  Every failure falls back one level: next decode
        candidate, then monolithic serving on this replica (whose
        re-prefill hits the prefix cache — export donated the prompt
        pages)."""
        loop = asyncio.get_event_loop()
        try:
            req = engine.submit_prefill(ids, max_tokens, request_id=rid)
        except ValueError as e:
            tracing.record_instant(rid, 'server.reject', status=413,
                                   prompt_tokens=len(ids),
                                   max_prompt_len=engine.max_prompt_len)
            return web.json_response(
                {'error': str(e),
                 'max_prompt_len': engine.max_prompt_len}, status=413)
        await loop.run_in_executor(None, req.tokens)
        if req.kv_export is None:
            # Engine died mid-prefill; serve the error like any other.
            return web.json_response(
                {'error': f'prefill failed: {engine.error!r}'},
                status=503)
        payload = await loop.run_in_executor(
            None, _export_payload, req, ids, max_tokens, rid)
        body, url = await kv_transfer.push(_session(), targets, payload,
                                           request_id=rid)
        if body is not None:
            body['request_id'] = rid
            body['disaggregated'] = True
            body['decode_url'] = url
            return web.json_response(body)
        logger.warning(f'every decode candidate failed for {rid}; '
                       f'serving monolithically')
        return await _serve_monolithic(ids, max_tokens, rid)

    async def completions(request):
        try:
            body = await request.json()
        except Exception:  # pylint: disable=broad-except
            return web.json_response({'error': 'invalid JSON'}, status=400)
        ids = body.get('prompt_ids')
        if ids is None:
            prompt = body.get('prompt')
            if not isinstance(prompt, str):
                return web.json_response(
                    {'error': 'need "prompt" or "prompt_ids"'}, status=400)
            ids = encode_bytes(prompt)
        max_tokens = int(body.get('max_tokens', 64))
        rid = request['skytpu_request_id']
        targets = kv_transfer.parse_decode_targets(
            request.headers.get(kv_transfer.DECODE_URL_HEADER))
        if role == 'prefill' and targets and engine.cfg.kv_page_size:
            return await _serve_prefill_handoff(ids, max_tokens, rid,
                                                targets)
        return await _serve_monolithic(ids, max_tokens, rid)

    async def kv_adopt(request):
        """Decode role: adopt a prefill replica's KV handoff and
        decode it to completion.  The response is the completions JSON
        so the pushing replica relays it verbatim."""
        raw = await request.read()
        rid = request['skytpu_request_id']
        try:
            handoff = kv_transfer.deserialize(raw)
        except ValueError as e:
            return web.json_response({'error': str(e)}, status=400)
        try:
            req = engine.submit_adopt(
                handoff.prompt_ids, handoff.first_token, handoff.leaves,
                handoff.max_new_tokens, request_id=rid,
                page_size=handoff.page_size)
        except ValueError as e:
            # Geometry mismatch (page size/count): this replica cannot
            # serve the payload — 422 tells the pusher to try another.
            return web.json_response({'error': str(e)}, status=422)
        except RuntimeError as e:
            return web.json_response({'error': str(e)}, status=503)
        out = await asyncio.get_event_loop().run_in_executor(
            None, req.tokens)
        return web.json_response(
            _completion_json(rid, handoff.prompt_ids, out, req))

    async def metrics_route(_request):
        return web.Response(text=metrics_lib.render(),
                            content_type='text/plain')

    # On-demand profiler capture (perf/profiler.py): artifacts live
    # under a retention-bounded store, wholly removed at shutdown so
    # long-lived replicas never grow disk without bound.
    profile_store = profiler_lib.ProfileStore()

    async def debug_profile(request):
        try:
            duration_ms = float(request.query.get('duration_ms', '500'))
        except ValueError:
            return web.json_response(
                {'error': 'duration_ms must be a number'}, status=400)
        if duration_ms <= 0:
            return web.json_response(
                {'error': 'duration_ms must be positive'}, status=400)
        rid = request['skytpu_request_id']
        loop = asyncio.get_event_loop()
        try:
            # Executor thread: capture() sleeps for the whole window.
            summary = await loop.run_in_executor(
                None, profile_store.capture, duration_ms, rid)
        except profiler_lib.CaptureBusy as e:
            return web.json_response({'error': str(e)}, status=409)
        summary['role'] = role
        return web.json_response(summary)

    async def debug_profile_artifact(request):
        try:
            path = profile_store.artifact_path(
                request.match_info['tail'])
        except (ValueError, FileNotFoundError) as e:
            return web.json_response({'error': str(e)}, status=404)
        return web.FileResponse(path)

    async def _cleanup_profiles(_app):
        profile_store.cleanup()

    debug_requests, debug_request = tracing.make_debug_handlers()

    app.router.add_get('/health', health)
    app.router.add_get('/metrics', metrics_route)
    app.router.add_get('/debug/requests', debug_requests)
    app.router.add_get('/debug/requests/{request_id}', debug_request)
    app.router.add_get('/debug/profile', debug_profile)
    app.router.add_get('/debug/profile/artifact/{tail:.+}',
                       debug_profile_artifact)
    app.router.add_post('/v1/completions', completions)
    app.router.add_post(kv_transfer.ADOPT_ROUTE, kv_adopt)
    app.on_cleanup.append(_close_session)
    app.on_cleanup.append(_cleanup_profiles)
    # Tests and embedders reach the store for retention assertions.
    app['skytpu_profile_store'] = profile_store
    return app


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='bench-600m')
    parser.add_argument('--port', type=int,
                        default=int(os.environ.get(
                            'SKYTPU_SERVE_REPLICA_PORT', '8200')))
    parser.add_argument('--n-slots', type=int, default=8)
    parser.add_argument('--max-seq-len', type=int, default=1024)
    parser.add_argument(
        '--max-prompt-len', type=int,
        default=int(os.environ.get('SKYTPU_SERVE_MAX_PROMPT_LEN', '0')),
        help='longest admissible prompt in tokens (0 = model limit, '
        'max_seq_len - 1).  Prompts beyond the largest prefill bucket '
        'are chunked and interleaved with decode, so this is a policy '
        'cap, not a capability one.  Serve specs set it via '
        'service.max_prompt_len, which arrives here as '
        'SKYTPU_SERVE_MAX_PROMPT_LEN.')
    parser.add_argument(
        '--tensor', type=int,
        default=int(os.environ.get('SKYTPU_SERVE_TENSOR', '1')),
        help='tensor-parallel degree: shard weights/KV cache over this '
        'many chips (must divide the model\'s head counts; 1 = '
        'single-chip engine).  Serve specs set it via '
        'service.tensor_parallel, which arrives here as '
        'SKYTPU_SERVE_TENSOR.')
    parser.add_argument(
        '--kv-page-size', type=int,
        default=int(os.environ.get('SKYTPU_SERVE_KV_PAGE_SIZE', '0')),
        help='paged KV cache: page size in tokens (must divide every '
        'prefill bucket and max_seq_len).  Admission then charges '
        'pages instead of reserving n_slots x max_seq_len of HBM, and '
        'shared prompt prefixes are prefilled once (--prefix-cache).  '
        '0 = the contiguous layout.  Serve specs set it via '
        'service.kv_page_size, which arrives here as '
        'SKYTPU_SERVE_KV_PAGE_SIZE.')
    parser.add_argument(
        '--kv-pages', type=int,
        default=int(os.environ.get('SKYTPU_SERVE_KV_PAGES', '0')),
        help='page-pool size (with --kv-page-size).  0 = full backing '
        '(n_slots x max_seq_len / page_size, no admission risk); '
        'smaller values cap KV HBM at pool size and let admission '
        'control — which charges actual request length — pack more '
        'slots than full reservation would.')
    parser.add_argument(
        '--prefix-cache', type=int, choices=(0, 1),
        default=int(os.environ.get('SKYTPU_SERVE_PREFIX_CACHE', '1')),
        help='radix prefix cache over the paged KV pool (needs '
        '--kv-page-size): requests sharing a page-aligned token '
        'prefix (system prompts, few-shot templates, multi-turn '
        'replays) reference the cached pages instead of prefilling '
        'them.  Serve specs set it via service.prefix_cache '
        '(SKYTPU_SERVE_PREFIX_CACHE).')
    parser.add_argument(
        '--kv-dtype', choices=('bf16', 'int8'),
        default=os.environ.get('SKYTPU_SERVE_KV_DTYPE', 'bf16'),
        help='KV-page storage dtype (needs --kv-page-size).  int8 '
        'quantizes pages at scatter time (per-page absmax scale '
        'stored alongside), halving the per-token KV read that '
        'bounds decode throughput.  Serve specs set it via '
        'service.kv_dtype (SKYTPU_SERVE_KV_DTYPE).')
    parser.add_argument(
        '--spec-ngram', type=int,
        default=int(os.environ.get('SKYTPU_SERVE_SPEC_NGRAM', '0')),
        help='self-speculative n-gram decoding: draft length k per '
        'verify step (needs --kv-page-size; 0 = off).  The engine '
        'drafts k tokens from each request\'s own history and '
        'verifies all k+1 positions in one fixed-shape dispatch.  '
        'Serve specs set it via service.speculation '
        '(SKYTPU_SERVE_SPEC_NGRAM).')
    parser.add_argument(
        '--role', choices=('monolithic', 'prefill', 'decode'),
        default=os.environ.get('SKYTPU_SERVE_ROLE', 'monolithic'),
        help='disaggregated serving role (requires --kv-page-size: '
        'pages are the KV-transfer unit).  `prefill` replicas run '
        'only the prefill phase when the serve LB names decode '
        'candidates (X-Skytpu-Decode-Url) and push the paged KV + '
        'first token to one of them; `decode` replicas accept '
        '/v1/kv_adopt.  Both run the full engine, so a mis-routed '
        'request still completes.  Serve specs set the pools via '
        'service.disaggregation, which arrives here as '
        'SKYTPU_SERVE_ROLE.')
    parser.add_argument(
        '--checkpoint', default=None,
        help='orbax checkpoint dir (local path or gs://bucket/prefix); '
        'restores trained params instead of random init')
    parser.add_argument(
        '--param-dtype', choices=['float32', 'bfloat16'], default=None,
        help='cast restored params (bfloat16 halves HBM — required to '
        'fit 7B-class models on one v5e chip)')
    args = parser.parse_args()
    if args.max_prompt_len < 0:
        # A negative cap would 413 every request while /health stays
        # green — refuse at startup instead of serving a dead replica.
        parser.error(f'--max-prompt-len must be >= 0, '
                     f'got {args.max_prompt_len}')

    import dataclasses
    import jax
    from skypilot_tpu.models.llama import LLAMA_CONFIGS, Llama, init_params

    cfg = dataclasses.replace(LLAMA_CONFIGS[args.model],
                              max_seq_len=args.max_seq_len)
    if args.param_dtype:
        cfg = dataclasses.replace(
            cfg, param_dtype=getattr(jax.numpy, args.param_dtype))
    mesh = None
    if args.tensor > 1:
        from skypilot_tpu.parallel.mesh import build_serve_mesh
        mesh = build_serve_mesh(args.tensor, n_heads=cfg.n_heads,
                                n_kv_heads=cfg.n_kv_heads)
    model = Llama(cfg, mesh)
    if args.checkpoint:
        from skypilot_tpu.inference.weights import (load_serving_params,
                                                    serving_shardings)
        shardings = (serving_shardings(model, mesh)
                     if mesh is not None else None)
        # Under a mesh each leaf lands directly in its sharded placement
        # — the full tree never exists on one chip.
        params = load_serving_params(args.checkpoint,
                                     dtype=cfg.param_dtype,
                                     shardings=shardings)
    else:
        logger.warning('no --checkpoint given: serving RANDOM-INIT params '
                       '(demo mode)')
        params = init_params(model, jax.random.PRNGKey(0))['params']
    engine = DecodeEngine(
        model, params,
        EngineConfig(n_slots=args.n_slots, mesh=mesh,
                     max_prompt_len=args.max_prompt_len or None,
                     kv_page_size=args.kv_page_size or None,
                     kv_pages=args.kv_pages or None,
                     prefix_cache=bool(args.prefix_cache),
                     kv_dtype=(args.kv_dtype
                               if args.kv_page_size else 'bf16'),
                     speculation=(args.spec_ngram
                                  if args.kv_page_size else 0)))
    # Compile every prefill shape before taking traffic — a mid-burst
    # XLA compile would stall the whole decode batch for seconds.
    engine.prewarm()
    engine.start()
    if args.role != 'monolithic' and not args.kv_page_size:
        # A roled replica without paging cannot hand KV off; serve
        # monolithically rather than advertise a capability it lacks.
        logger.warning(f'--role {args.role} requires --kv-page-size; '
                       f'serving monolithically')
        args.role = 'monolithic'
    logger.info(f'serving {args.model} on :{args.port} '
                f'({args.n_slots} slots, tensor={args.tensor}, '
                f'role={args.role}, '
                f'kv_page_size={args.kv_page_size or "off"}, '
                f'prefix_cache='
                f'{bool(args.prefix_cache and args.kv_page_size)}, '
                f'kv_dtype='
                f'{args.kv_dtype if args.kv_page_size else "bf16"}, '
                f'speculation='
                f'{args.spec_ngram if args.kv_page_size else 0}, '
                f'checkpoint={args.checkpoint or "random-init"})')
    web.run_app(build_app(engine, role=args.role), port=args.port,
                print=None)


if __name__ == '__main__':
    main()
