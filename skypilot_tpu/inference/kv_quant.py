"""Int8 KV-page quantization: the device-side counterpart of paging.py.

paging.py owns page *bookkeeping* and never touches a device array;
this module owns the page *payload* when ``EngineConfig.kv_dtype ==
'int8'``.  A quantized pool stores each (layer, K|V) cache as a
:class:`QuantPages` pair instead of a single dense array:

- ``data``:  int8  ``[n_pages, n_kv_heads, page_size, head_dim]``
- ``scale``: f32   ``[n_pages, n_kv_heads, page_size]``

i.e. symmetric absmax quantization along ``head_dim``, one scale per
(page, kv-head, position).  That granularity keeps dequantization a
single fused multiply inside the attention gather while halving the
dominant HBM stream on decode (the int8 payload; the f32 scales add
``4 / head_dim`` bytes per element — ~3% at head_dim 128, accounted
for explicitly by ``perf/cost_model.py``).

``QuantPages`` is a registered pytree node, so every structural path
in the engine — pool init, donation, per-leaf KV export/adopt wire
format, sharding-spec mapping, prewarm zeroing — descends into the
(data, scale) pair without modification.  Only the scatter/gather
sites (quantize on insert, dequantize on read) branch on the type.

Quantization is *idempotent under round-trip*: dequantizing a page
and re-quantizing it reproduces bit-identical (data, scale), because
absmax of ``q * s`` is ``127 * s`` by construction.  The radix-cache
shared-prefix invariant (re-inserting a cached prefix writes back
value-identical pages) therefore survives quantization exactly.
"""
from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

# Guard against zero scales on all-zero rows (e.g. freshly zeroed
# pool pages round-tripped through dequant/requant).
_EPS = 1e-8


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QuantPages:
    """An int8 page pool leaf: quantized payload + per-position scales.

    ``data``  int8 ``[..., page_size, head_dim]``
    ``scale`` f32  ``[..., page_size]`` (one absmax scale per row of
    ``head_dim`` elements).
    """
    data: Any
    scale: Any

    def tree_flatten(self):
        return (self.data, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        del aux
        return cls(*children)


def quantize_kv(x):
    """Symmetric absmax int8 quantization along the last axis.

    Returns ``(q, s)`` with ``q`` int8 of ``x.shape`` and ``s`` f32 of
    ``x.shape[:-1]`` such that ``q * s[..., None] ~= x``.
    """
    x32 = x.astype(jnp.float32)
    s = jnp.max(jnp.abs(x32), axis=-1) / 127.0
    q = jnp.clip(jnp.round(x32 / jnp.maximum(s, _EPS)[..., None]),
                 -127.0, 127.0).astype(jnp.int8)
    return q, s


def dequantize_kv(q, s, dtype):
    """Inverse of :func:`quantize_kv` (up to rounding), cast to
    ``dtype`` for the attention matmul."""
    return (q.astype(jnp.float32) * s[..., None]).astype(dtype)
