"""RL fine-tuning primitives: policy-gradient updates over sampled
continuations (the first-party twin of the reference's RL recipe
integration, llm/verl/ — which delegates the math to an external
framework; here the loop is native so it runs the same engine + trainer
stack as everything else, SURVEY.md §2.15).

The pattern, TPU-first:
- ROLLOUT on the serving engine (inference/engine.py): sampling runs in
  the continuous-batching decode loop at serving efficiency — the
  actor's forward pass is the same bandwidth-optimal program that
  serves traffic (temperature > 0 for exploration);
- UPDATE with one jitted program: a REINFORCE/GRPO-style masked
  log-prob loss whose forward is a standard teacher-forced pass over
  [prompt + sampled] — one big MXU matmul batch, no per-token Python;
- advantages are plain host arrays (reward whitening happens host-side
  where reward functions live);
- SWAP with engine.update_params WITHOUT draining: the learner's tree
  stages into the engine's committed layouts and installs at the decode
  loop's next dispatch boundary (double-buffered), so the
  rollout/update alternation never stops serving.

This is deliberately the PRIMITIVE layer: PPO ratios/KL penalties
compose on top by passing `ref_logprobs`; the example recipe
(examples/train_rl_reinforce.yaml) shows the whole loop.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def sequence_logprobs(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Per-position log p(tokens[t] | tokens[<t]) from next-token
    logits: logits[:, t] predicts tokens[:, t+1].  Returns [B, S-1]."""
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    return jnp.take_along_axis(logp, tokens[:, 1:, None],
                               axis=-1)[..., 0]


def reinforce_loss(logits: jax.Array, tokens: jax.Array,
                   advantages: jax.Array, prompt_lens: jax.Array,
                   total_lens: jax.Array,
                   ref_logprobs: Optional[jax.Array] = None,
                   kl_coef: float = 0.0) -> jax.Array:
    """REINFORCE objective over each row's SAMPLED region only.

    tokens [B, S] = prompt + sampled continuation, zero-padded to S
    (teacher-forced); advantages [B] (whitened rewards); prompt_lens
    and total_lens are PER-ROW [B] — rows may have different prompt
    and continuation lengths, and padding beyond total_lens must never
    reach the gradient (it would push probability mass onto the pad
    token for positively-advantaged rows).  Optional KL regularization
    toward a reference policy's per-token logprobs (PPO-lite: keeps the
    policy near the base model).
    """
    logprobs = sequence_logprobs(logits, tokens)          # [B, S-1]
    positions = jnp.arange(tokens.shape[1] - 1)[None, :]
    mask = ((positions >= prompt_lens[:, None] - 1) &
            (positions < total_lens[:, None] - 1)).astype(logprobs.dtype)
    pg = -(advantages[:, None] * logprobs * mask).sum() / \
        jnp.maximum(mask.sum(), 1.0)
    if ref_logprobs is not None and kl_coef > 0.0:
        kl = ((logprobs - ref_logprobs) * mask).sum() / \
            jnp.maximum(mask.sum(), 1.0)
        pg = pg + kl_coef * kl
    return pg


def whiten(rewards: np.ndarray) -> np.ndarray:
    """Standard advantage whitening (mean 0, std 1; std floor for the
    all-equal case)."""
    # skytpu: allow-sync(rewards are host floats from reward_fn — np here is host math, nothing device-side)
    rewards = np.asarray(rewards, np.float32)
    return (rewards - rewards.mean()) / max(float(rewards.std()), 1e-6)


def make_reinforce_step(model, tx, kl_coef: float = 0.0):
    """Jitted (params, opt_state, tokens, advantages, prompt_lens,
    total_lens[, ref_logprobs]) -> (params, opt_state, loss).  One
    compiled program per (B, S) shape — pad rollout batches to fixed
    shapes the usual way (lengths are traced values, not shapes)."""
    import optax

    def step(params, opt_state, tokens, advantages, prompt_lens,
             total_lens, ref_logprobs=None):
        def loss_fn(p):
            logits = model.apply({'params': p}, tokens)
            return reinforce_loss(logits, tokens, advantages,
                                  prompt_lens, total_lens,
                                  ref_logprobs, kl_coef)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    # Donate opt_state (fresh buffers from tx.init, learner-private,
    # rebound every update): XLA reuses the Adam moments in place —
    # 2x param bytes a 7B learner no longer holds twice mid-update.
    # Params are deliberately NOT donated: in the co-located
    # actor-learner mode the serving engine's tree may ALIAS this one
    # (DecodeEngine's device_put is zero-copy when placement matches),
    # and donating would delete buffers the decode loop still
    # dispatches against between rollout and update_params.
    return jax.jit(step, donate_argnums=(1,))


def rollout(engine, prompts: List[List[int]],  # skytpu: hot-entry
            max_new_tokens: int,
            reward_fn: Callable[[List[int], List[int]], float]
            ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Sample continuations on the decode engine and score them.

    Returns (tokens [B, S] zero-padded, advantages [B],
    prompt_lens [B], total_lens [B]) — per-row lengths feed
    reinforce_loss's mask so padding and unequal prompts never reach
    the gradient.  The engine must be constructed with temperature > 0
    (greedy rollouts collapse the policy gradient to a point mass).
    """
    reqs = [engine.submit(p, max_new_tokens) for p in prompts]
    while any(r.finished_at is None for r in reqs):
        engine.step_pipelined()
    # No drain: the retire-lag call left in flight is garbage the next
    # rollout's first step discards, and update_params no longer needs
    # an idle engine — the learner's new tree installs at the next
    # dispatch boundary while serving continues (double-buffered swap).
    sampled = [r.tokens() for r in reqs]
    rewards = [reward_fn(p, s) for p, s in zip(prompts, sampled)]
    # skytpu: allow-sync(host-side batch assembly AFTER the rollout finished — tokens already left the device via the engine's one-sync-per-step fetch)
    prompt_lens = np.asarray([len(p) for p in prompts], np.int32)
    # skytpu: allow-sync(same: host lists only, the device is not involved)
    total_lens = np.asarray(
        [len(p) + len(s) for p, s in zip(prompts, sampled)], np.int32)
    tokens = np.zeros((len(prompts), int(total_lens.max())), np.int32)
    for i, (p, s) in enumerate(zip(prompts, sampled)):
        seq = list(p) + list(s)
        tokens[i, :len(seq)] = seq
    return tokens, whiten(rewards), prompt_lens, total_lens
