"""Model-FLOP accounting shared by bench.py and the trainer's MFU
gauges, so the benchmark and the live skytpu_train_mfu_percent series
report the same quantity.

MFU here is *model* FLOPs utilization: achieved model FLOPs/s (6N dense
fwd+bwd plus the causal-attention term) over the chip's peak bf16
throughput.  Hardware-neutral — the reference's published v6e numbers
reduce to the same measure (see bench.py's baseline derivation).
"""
from __future__ import annotations

from typing import Optional

PEAK_BF16_TFLOPS = {
    'v5litepod': 197.0,
    'v5e': 197.0,
    'v6e': 918.0,
    'v5p': 459.0,
    'v4': 275.0,
    'cpu': 1.0,  # nominal, so accounting runs anywhere
}


def chip_kind() -> str:
    """Normalized device-kind name of the first local device."""
    import jax
    dev = jax.devices()[0]
    kind = getattr(dev, 'device_kind', 'cpu').lower().replace(' ', '')
    for name in PEAK_BF16_TFLOPS:
        if name in kind:
            return name
    if 'lite' in kind:      # 'TPU v5 lite'
        return 'v5litepod'
    return 'cpu'


def train_flops_per_token(n_params: int, n_layers: int, dim: int,
                          seq_len: int) -> float:
    """fwd+bwd model FLOPs per trained token: 6N dense + causal
    attention term."""
    return 6 * n_params + 6 * n_layers * seq_len * dim


def estimate_mfu(tokens_per_s: float, n_params: int, n_layers: int,
                 dim: int, seq_len: int, n_chips: int = 1,
                 kind: Optional[str] = None) -> float:
    """Achieved model TFLOP/s as % of the slice's peak bf16 TFLOP/s.

    Returns 0.0 on unrecognized hardware rather than a bogus ratio."""
    kind = kind or chip_kind()
    peak = PEAK_BF16_TFLOPS.get(kind)
    if not peak or tokens_per_s <= 0:
        return 0.0
    achieved_tflops = (tokens_per_s *
                       train_flops_per_token(n_params, n_layers, dim,
                                             seq_len) / 1e12)
    return 100.0 * achieved_tflops / (peak * max(1, n_chips))


def train_hbm_bytes_per_token(n_params: int, tokens_per_step: int,
                              param_bytes: int = 2,
                              opt_state_bytes: int = 8) -> float:
    """Modeled HBM traffic per trained token: the trainer twin of the
    decode cost model's bytes/token gauge (perf/cost_model.py).

    One optimizer step streams the weight tree through HBM a fixed
    number of times — forward read + backward read (2x params), the
    gradient write (1x), and the Adam moment read-modify-write (2x the
    f32 m/v pair) — all amortized over the step's token count.
    Activation traffic is recompute-dominated under remat and omitted;
    this is a floor, matching the decode model's roofline role."""
    if tokens_per_step <= 0:
        return 0.0
    step_bytes = n_params * (3 * param_bytes + 2 * opt_state_bytes)
    return step_bytes / tokens_per_step


def train_arith_intensity(n_params: int, n_layers: int, dim: int,
                          seq_len: int, tokens_per_step: int,
                          param_bytes: int = 2,
                          opt_state_bytes: int = 8) -> float:
    """FLOPs per modeled HBM byte for one train step."""
    bytes_per_token = train_hbm_bytes_per_token(
        n_params, tokens_per_step, param_bytes, opt_state_bytes)
    if bytes_per_token <= 0:
        return 0.0
    return train_flops_per_token(n_params, n_layers, dim,
                                 seq_len) / bytes_per_token
