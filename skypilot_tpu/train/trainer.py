"""Sharded training loop for decoder LMs.

The recipe engine the reference delegates to torch/FSDP/DeepSpeed YAMLs
(SURVEY.md §2.15) — here it is a library: pick a mesh plan (dp/fsdp/tp),
and the factory turns a Flax model with logical-axis annotations into a
fully-sharded, jitted train step:

- parameter/optimizer shardings derived from the model's logical axes via
  `nn.logical_to_mesh_sharding` (ZeRO-3-style fsdp sharding without any
  model change);
- batch sharded over (data, fsdp);
- bf16 compute, f32 params/optimizer; loss in f32;
- donated state (in-place buffer reuse on TPU);
- XLA inserts the all-reduce/all-gather/reduce-scatter collectives implied
  by the sharding — nothing here calls a collective by hand.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Iterator, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from flax.training import train_state as flax_train_state
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from skypilot_tpu.parallel import sharding as sharding_lib


class TrainState(flax_train_state.TrainState):
    """flax TrainState; kept as a named subclass for checkpoint stability."""


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    learning_rate: float = 3e-4
    warmup_steps: int = 100
    total_steps: int = 10000
    weight_decay: float = 0.1
    grad_clip: float = 1.0
    b1: float = 0.9
    b2: float = 0.95


def make_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=cfg.learning_rate,
        warmup_steps=cfg.warmup_steps, decay_steps=cfg.total_steps,
        end_value=cfg.learning_rate * 0.1)
    return optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip),
        optax.adamw(schedule, b1=cfg.b1, b2=cfg.b2,
                    weight_decay=cfg.weight_decay),
    )


def make_train_state(
    model: nn.Module,
    mesh: Mesh,
    rng: jax.Array,
    sample_tokens: jax.Array,
    train_cfg: Optional[TrainConfig] = None,
    rules=None,
) -> Tuple[TrainState, Any]:
    """Initialize a sharded TrainState directly on the mesh.

    Returns (state, state_shardings).  Params are materialized *sharded*
    (jit with out_shardings), so a model larger than one chip's HBM never
    exists unsharded.
    """
    rules = list(rules or sharding_lib.DEFAULT_RULES)
    tx = make_optimizer(train_cfg or TrainConfig())

    def create() -> TrainState:
        variables = model.init(rng, sample_tokens)
        return TrainState.create(apply_fn=model.apply,
                                 params=variables['params'], tx=tx)

    abstract = jax.eval_shape(create)
    logical_specs = nn.get_partition_spec(abstract)
    shardings = nn.logical_to_mesh_sharding(logical_specs, mesh, rules)
    state = jax.jit(create, out_shardings=shardings)()
    state = nn.meta.unbox(state)
    shardings_unboxed = nn.meta.unbox(shardings)
    return state, shardings_unboxed


def lm_loss(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Next-token CE.  tokens [B, S]; logits [B, S, V] (predicting t+1)."""
    targets = tokens[:, 1:]
    logits = logits[:, :-1]
    losses = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), targets)
    return losses.mean()


def make_sharded_train_step(
    mesh: Mesh,
    state_shardings,
    loss_fn: Callable[[jax.Array, jax.Array], jax.Array] = lm_loss,
) -> Callable[[TrainState, jax.Array], Tuple[TrainState, dict]]:
    """Jitted train step: donated state in, sharded state out."""
    batch_sharding = sharding_lib.batch_sharding(mesh)

    def step(state: TrainState, tokens: jax.Array):
        def compute_loss(params):
            logits = state.apply_fn({'params': params}, tokens)
            return loss_fn(logits, tokens)

        loss, grads = jax.value_and_grad(compute_loss)(state.params)
        new_state = state.apply_gradients(grads=grads)
        metrics = {
            'loss': loss,
            'grad_norm': optax.global_norm(grads),
            'step': new_state.step,
        }
        return new_state, metrics

    return jax.jit(
        step,
        in_shardings=(state_shardings, batch_sharding),
        out_shardings=(state_shardings, None),
        donate_argnums=(0,),
    )


class Trainer:
    """Minimal driver: steps, metrics, periodic checkpointing."""

    def __init__(self, model: nn.Module, mesh: Mesh, rng: jax.Array,
                 sample_tokens: jax.Array,
                 train_cfg: Optional[TrainConfig] = None,
                 checkpoint_dir: Optional[str] = None,
                 rules=None,
                 phases=None,
                 host: Optional[str] = None) -> None:
        from skypilot_tpu.obs import goodput as goodput_lib
        self._gp = goodput_lib
        # Goodput phase recorder: classifies this process's wall-clock
        # (a managed job exports SKYTPU_GOODPUT_JOB and gets the
        # durable ledger; otherwise gauges + flight recorder only).
        # Opened BEFORE state init so sharded-init + step compilation
        # land in init_compile, not unclassified.
        self.phases = (phases if phases is not None
                       else goodput_lib.PhaseRecorder.from_env())
        self.phases.begin(goodput_lib.INIT_COMPILE)
        # Host identity for the per-host step-time histogram label
        # (straggler skew is computed across these).
        self.host = (host if host is not None
                     else f'host{jax.process_index()}')
        self._badput_exported: dict = {}
        self.model = model
        self.mesh = mesh
        self.state, self.shardings = make_train_state(
            model, mesh, rng, sample_tokens, train_cfg, rules)
        self.train_step = make_sharded_train_step(mesh, self.shardings)
        self.checkpoint_dir = checkpoint_dir
        self._ckpt_mgr = None
        if checkpoint_dir is not None:
            from skypilot_tpu.train import checkpoint as ckpt_lib
            self._ckpt_mgr = ckpt_lib.CheckpointManager(checkpoint_dir)

    def restore_if_available(self) -> int:
        """Resume from the newest checkpoint (preemption recovery path:
        managed jobs rely on this after a slice is recreated)."""
        if self._ckpt_mgr is None:
            return 0
        step = self._ckpt_mgr.latest_step()
        if step is None:
            return 0
        self.phases.begin(self._gp.CHECKPOINT_RESTORE)
        self.state = self._ckpt_mgr.restore(step, self.state)
        self.phases.begin(self._gp.INIT_COMPILE)
        return step

    def run(self, data: Iterator[jax.Array],  # skytpu: hot-entry
            num_steps: int,
            checkpoint_every: int = 0,
            log_every: int = 10,
            log_fn: Callable[[dict], None] = None) -> dict:
        from skypilot_tpu.server import metrics as metrics_lib
        gp = self._gp
        phases = self.phases
        metrics = {}
        t0 = time.perf_counter()
        tokens_seen = 0
        prev = t0
        # Gauges export WINDOWED throughput (since the last log
        # boundary), matching their _HELP text — the cumulative average
        # returned below would mask a mid-run stall and bakes step-0
        # compile time into the denominator forever.
        window_tokens = 0
        window_start = t0
        if phases.category != gp.INIT_COMPILE:
            phases.begin(gp.INIT_COMPILE, t0)
        # Non-productive seconds of THIS run (compile window, checkpoint
        # saves, input stalls): subtracted from every throughput
        # denominator, so a checkpoint-heavy run's tokens/s measures
        # training speed, not orbax speed.
        nonprod_s = 0.0
        window_nonprod = 0.0
        window_stall = 0.0
        for i in range(num_steps):
            fetch_t = time.perf_counter()
            batch = next(data)
            stall = time.perf_counter() - fetch_t
            tokens_seen += batch.size
            window_tokens += batch.size
            self.state, metrics = self.train_step(self.state, batch)
            # Host wall time per iteration: async dispatch, but donated
            # buffers backpressure the host to the device step rate at
            # steady state — and no sync is added here.
            now = time.perf_counter()
            if i > 0:
                window_stall += stall
                metrics_lib.observe_hist('skytpu_train_step_seconds',
                                         now - prev, host=self.host)
            else:
                # Step 0 is dominated by XLA trace+compile; one such
                # sample would inflate the histogram sum (and the first
                # throughput window) for the whole run.
                window_tokens = 0
                window_start = now
                nonprod_s += now - t0
                phases.begin(gp.PRODUCTIVE, now)
            if checkpoint_every and (i + 1) % checkpoint_every == 0:
                ck0 = time.perf_counter()
                phases.begin(gp.CHECKPOINT_SAVE, ck0)
                self.save_checkpoint()
                ck1 = time.perf_counter()
                phases.begin(gp.PRODUCTIVE, ck1)
                nonprod_s += ck1 - ck0
                window_nonprod += ck1 - ck0
            if (i + 1) % log_every == 0:
                # Gauges export on every boundary, log_fn or not — a
                # run launched without a log callback must still be
                # scrapeable mid-flight.  (Donated buffers bound how
                # far dispatch runs ahead, so the wall-clock window is
                # honest without forcing a sync here.)
                phases.carve(gp.INPUT_STALL, window_stall)
                nonprod_s += window_stall
                window_nonprod += window_stall
                elapsed = time.perf_counter() - window_start
                self._export_throughput(
                    window_tokens / max(elapsed - window_nonprod, 1e-9),
                    batch)
                self._export_goodput()
                if log_fn:
                    # skytpu: allow-sync(log-boundary read only, and the fetch is of an ALREADY-retired step's metrics — dispatch stays ahead)
                    m = jax.device_get(metrics)
                    m['tokens_per_s'] = tokens_seen / max(
                        time.perf_counter() - t0 - nonprod_s, 1e-9)
                    log_fn(m)
                window_tokens = 0
                window_stall = 0.0
                window_nonprod = 0.0
                window_start = time.perf_counter()
            # Re-stamp AFTER checkpoint/log work: a multi-second orbax
            # save attributed to the next step would spike the step-time
            # p99 every checkpoint interval.
            prev = time.perf_counter()
        phases.carve(gp.INPUT_STALL, window_stall)
        nonprod_s += window_stall
        window_nonprod += window_stall
        end = time.perf_counter()
        # Roll (flush) the open interval at run end: a job preempted a
        # second from now keeps this run's productive seconds in the
        # durable ledger.
        if phases.category is not None:
            phases.begin(phases.category, end)
        # skytpu: allow-sync(end of run: the final metrics fetch, after the last step)
        out = jax.device_get(metrics)
        out['tokens_per_s'] = tokens_seen / max(end - t0 - nonprod_s,
                                                1e-9)
        if window_tokens:
            self._export_throughput(
                window_tokens / max(end - window_start - window_nonprod,
                                    1e-9),
                batch)
        self._export_goodput()
        return out

    def _export_goodput(self) -> None:
        """Goodput gauge + badput counter deltas from the recorder's
        live snapshot — scrape-visible mid-flight, like the throughput
        gauges (no db write, no sync)."""
        from skypilot_tpu.server import metrics as metrics_lib
        snap = self.phases.snapshot()
        wall = sum(snap.values())
        if wall <= 0:
            return
        metrics_lib.set_gauge(
            metrics_lib.TRAIN_GOODPUT_FAMILY,
            100.0 * snap.get(self._gp.PRODUCTIVE, 0.0) / wall)
        for cat in self._gp.BADPUT_CATEGORIES:
            total = snap.get(cat, 0.0)
            delta = total - self._badput_exported.get(cat, 0.0)
            if delta > 0:
                metrics_lib.inc_counter(metrics_lib.TRAIN_BADPUT_FAMILY,
                                        delta, category=cat)
                self._badput_exported[cat] = total

    def _export_throughput(self, tokens_per_s: float, batch) -> None:
        """tokens/sec + estimated-MFU gauges (bench.py's FLOP
        accounting via train/flops.py).  Models without a LlamaConfig-
        shaped cfg just skip the MFU gauge."""
        from skypilot_tpu.server import metrics as metrics_lib
        from skypilot_tpu.train import flops as flops_lib
        metrics_lib.set_gauge('skytpu_train_tokens_per_second',
                              tokens_per_s)
        cfg = getattr(self.model, 'cfg', None)
        if batch is None or cfg is None:
            return
        try:
            n_params = cfg.num_params()
            mfu = flops_lib.estimate_mfu(
                tokens_per_s, n_params, cfg.n_layers, cfg.dim,
                seq_len=batch.shape[-1], n_chips=self.mesh.size)
        except (AttributeError, TypeError):
            return      # cfg not LlamaConfig-shaped: no MFU gauge
        if mfu > 0:
            metrics_lib.set_gauge('skytpu_train_mfu_percent', mfu)
        # Device-cost twins of the decode engine's perf gauges
        # (perf/cost_model.py): modeled HBM bytes per trained token and
        # the resulting arithmetic intensity, from the same shared FLOP
        # accounting.
        tokens_per_step = int(batch.size)
        hbm_bytes = flops_lib.train_hbm_bytes_per_token(
            n_params, tokens_per_step)
        if hbm_bytes > 0:
            metrics_lib.set_gauge('skytpu_train_hbm_bytes_per_token',
                                  hbm_bytes)
            metrics_lib.set_gauge(
                'skytpu_train_arith_intensity',
                flops_lib.train_arith_intensity(
                    n_params, cfg.n_layers, cfg.dim,
                    seq_len=batch.shape[-1],
                    tokens_per_step=tokens_per_step))

    def save_checkpoint(self) -> None:
        if self._ckpt_mgr is not None:
            # skytpu: allow-sync(checkpoint boundary: orbax serializes the whole tree anyway — the step read adds nothing)
            self._ckpt_mgr.save(int(jax.device_get(self.state.step)),
                                self.state)
