"""Training: sharded train-step factory, checkpointing, data pipeline."""
from skypilot_tpu.train.trainer import (Trainer, TrainConfig,
                                        make_sharded_train_step,
                                        make_train_state)

__all__ = ['Trainer', 'TrainConfig', 'make_sharded_train_step',
           'make_train_state']
