"""Checkpointing via orbax — the preemption-recovery backbone.

The reference's documented recovery pattern is "checkpoint to a MOUNT
bucket, reload on recover" (docs/source/examples/managed-jobs.rst:282-289);
managed jobs here follow the same convention, with orbax doing sharded,
async-friendly saves that restore onto a *different* mesh shape if the
recovered slice differs (orbax resharding).
"""
from __future__ import annotations

import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3) -> None:
        self.directory = os.path.abspath(os.path.expanduser(directory))
        os.makedirs(self.directory, exist_ok=True)
        options = ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                               create=True)
        self._mgr = ocp.CheckpointManager(self.directory, options=options)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def save(self, step: int, state: Any, wait: bool = False) -> None:
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mgr.wait_until_finished()

    def restore(self, step: int, target: Any) -> Any:
        """Restore into `target`'s structure/shardings (reshard on load)."""
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, target)
        return self._mgr.restore(step,
                                 args=ocp.args.StandardRestore(abstract))

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()
