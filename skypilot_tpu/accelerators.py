"""Accelerator registry — TPUs are the primary citizen.

The reference keeps a GPU-centric registry where names prefixed ``tpu-`` bypass
the canonical GPU list (sky/utils/accelerator_registry.py:13-30) and TPU host
topology is hardcoded in the GCP cloud (sky/clouds/gcp.py:717-768,
cloud_vm_ray_backend.py:2485 `num_ips_per_node`).  Here the registry is built
the other way around: every TPU generation carries its full hardware model —
chips, hosts, ICI topology, HBM, peak FLOP/s — because the optimizer, the
provisioner (slice shapes), the gang executor (hosts per slice) and the mesh
builder (ICI axes) all consume it.
"""
from __future__ import annotations

import dataclasses
import functools
import math
import re
from typing import Dict, List, Optional, Tuple

from skypilot_tpu import exceptions


@dataclasses.dataclass(frozen=True)
class TpuGeneration:
    """Static hardware model of one TPU generation."""
    name: str                     # canonical, e.g. 'v5p'
    aliases: Tuple[str, ...]      # accepted in accelerator strings
    cores_per_chip: int           # suffix in GCP type names counts cores for
                                  # v2..v5p, chips for v5e/v6e
    suffix_counts_chips: bool     # True for v5litepod / v6e
    multi_host_chips: int         # chips/host in multi-host slices (4 on all
                                  # current generations)
    small_slice_host_chips: int   # max chips of a single-host slice
    hbm_gb_per_chip: float
    bf16_tflops_per_chip: float   # peak dense bf16
    int8_tops_per_chip: float
    ici_dims: int                 # 2 = 2D torus (v2/v3/v5e/v6e), 3 = 3D (v4/v5p)
    default_runtime_version: str
    host_vcpus: int
    host_memory_gb: float
    min_chips: int = 1
    max_chips: int = 8192


# Peak numbers from public Cloud TPU system architecture docs.  max_chips
# reflects the largest pod slice GCP allocates per generation.
GENERATIONS: Dict[str, TpuGeneration] = {
    'v2': TpuGeneration('v2', ('v2',), 2, False, 4, 4, 16, 45, 0, 2,
                        'tpu-vm-base', 96, 334, min_chips=4, max_chips=256),
    'v3': TpuGeneration('v3', ('v3',), 2, False, 4, 4, 32, 123, 0, 2,
                        'tpu-vm-base', 96, 334, min_chips=4, max_chips=1024),
    'v4': TpuGeneration('v4', ('v4',), 2, False, 4, 4, 32, 275, 275, 3,
                        'tpu-vm-v4-base', 240, 400, min_chips=4,
                        max_chips=4096),
    'v5litepod': TpuGeneration('v5litepod', ('v5litepod', 'v5e', 'v5lite'), 1,
                               True, 4, 8, 16, 197, 394, 2,
                               'v2-alpha-tpuv5-lite', 224, 400, min_chips=1,
                               max_chips=256),
    'v5p': TpuGeneration('v5p', ('v5p',), 2, False, 4, 4, 95, 459, 918, 3,
                         'v2-alpha-tpuv5', 208, 448, min_chips=4,
                         max_chips=6144),
    'v6e': TpuGeneration('v6e', ('v6e', 'trillium'), 1, True, 4, 8, 32, 918,
                         1836, 2, 'v2-alpha-tpuv6e', 180, 720, min_chips=1,
                         max_chips=256),
}

_ALIAS_TO_GEN: Dict[str, str] = {}
for _g in GENERATIONS.values():
    for _a in _g.aliases:
        _ALIAS_TO_GEN[_a] = _g.name

_TPU_RE = re.compile(
    r'^tpu[-_]?(?P<gen>v[0-9]+[a-z]*(?:pod|lite)?|trillium)'
    r'(?:[-:](?P<count>\d+)(?:x(?P<slices>\d+))?)?$', re.IGNORECASE)


@dataclasses.dataclass(frozen=True)
class TpuType:
    """A parsed, concrete TPU slice request, e.g. ``tpu-v5p-128``.

    ``tpu-v5e-64x2`` requests a MULTISLICE cluster: ``num_slices``
    identically-shaped slices provisioned together and wired over DCN via
    the libtpu MEGASCALE env contract (parallel/distributed.py).  All
    per-shape properties (chips, hosts, HBM, TFLOPs) describe ONE slice;
    callers scale by ``num_slices`` where the whole cluster is meant.
    """
    generation: str          # canonical generation name
    count_suffix: int        # the number in the accelerator string
    topology: Optional[str] = None   # e.g. '4x4x8'; None = provider default
    num_slices: int = 1      # >1 = multislice (DCN-connected) cluster

    @property
    def gen(self) -> TpuGeneration:
        return GENERATIONS[self.generation]

    @property
    def num_chips(self) -> int:
        g = self.gen
        if g.suffix_counts_chips:
            return self.count_suffix
        return self.count_suffix // g.cores_per_chip

    @property
    def num_cores(self) -> int:
        g = self.gen
        if g.suffix_counts_chips:
            return self.count_suffix * g.cores_per_chip
        return self.count_suffix

    @property
    def num_hosts(self) -> int:
        """Host VMs in the slice.

        Matches GCP slice shapes: single-host up to a full host's chips;
        multi-host slices use 4-chip hosts for every generation (v5e-16 and
        v6e-16 are 4 hosts x 4 chips; reference observed the same fan-out via
        `num_ips_per_node`, cloud_vm_ray_backend.py:2485,:5940).
        """
        g = self.gen
        chips = self.num_chips
        if chips <= g.small_slice_host_chips:
            return 1
        return max(1, math.ceil(chips / g.multi_host_chips))

    @property
    def chips_per_host(self) -> int:
        return self.num_chips // self.num_hosts

    @property
    def is_pod(self) -> bool:
        """Multi-host slice.  Pods cannot be stopped, only deleted
        (reference: sky/clouds/gcp.py:219-226)."""
        return self.num_hosts > 1

    @property
    def name(self) -> str:
        """Canonical accelerator string, e.g. ``tpu-v5p-128`` or (multislice)
        ``tpu-v5e-64x2`` — round-trips through parse_tpu."""
        base = f'tpu-{self.generation}-{self.count_suffix}'
        return f'{base}x{self.num_slices}' if self.num_slices > 1 else base

    @property
    def slice_name(self) -> str:
        """Per-slice accelerator name (no multislice suffix) — what each
        provisioned node actually is."""
        return f'tpu-{self.generation}-{self.count_suffix}'

    @property
    def gcp_accelerator_type(self) -> str:
        """The TPU API acceleratorType of ONE slice, e.g. ``v5p-128``."""
        return f'{self.generation}-{self.count_suffix}'

    @property
    def runtime_version(self) -> str:
        return self.gen.default_runtime_version

    @property
    def hbm_gb(self) -> float:
        return self.num_chips * self.gen.hbm_gb_per_chip

    @property
    def bf16_tflops(self) -> float:
        return self.num_chips * self.gen.bf16_tflops_per_chip

    def default_topology(self) -> Tuple[int, ...]:
        """ICI mesh shape for the slice (used to build `jax.sharding.Mesh`).

        3D generations (v4/v5p) get an x,y,z torus; 2D generations a x,y
        grid.  Chosen as the most-square factorization, which is what the
        TPU API allocates by default.
        """
        chips = self.num_chips
        dims = self.gen.ici_dims
        if dims == 2:
            x = 1
            for f in range(int(math.isqrt(chips)), 0, -1):
                if chips % f == 0:
                    x = f
                    break
            return (x, chips // x)
        # 3D: factor into near-cube
        best = (1, 1, chips)
        best_score = float('inf')
        for a in range(1, int(round(chips ** (1 / 3))) + 2):
            if chips % a:
                continue
            rest = chips // a
            for b in range(a, int(math.isqrt(rest)) + 1):
                if rest % b:
                    continue
                c = rest // b
                score = (c - a) + (c - b)
                if score < best_score:
                    best_score = score
                    best = (a, b, c)
        return best

    def __str__(self) -> str:
        return self.name


def alias_to_generation() -> Dict[str, str]:
    """Accepted alias → canonical generation name (e.g. 'v5e'→'v5litepod')."""
    return dict(_ALIAS_TO_GEN)


def is_tpu(accelerator: Optional[str]) -> bool:
    """True iff the accelerator string names a TPU (analog of
    gcp_utils.is_tpu, sky/clouds/utils/gcp_utils.py:30-50)."""
    return accelerator is not None and bool(_TPU_RE.match(accelerator.strip()))


@functools.lru_cache(maxsize=4096)
def parse_tpu(accelerator: str) -> TpuType:
    """Parse ``tpu-v5p-128`` / ``tpu-v6e:8`` / ``tpu-v5e-16`` into a TpuType."""
    m = _TPU_RE.match(accelerator.strip())
    if not m:
        raise exceptions.InvalidAcceleratorError(
            f'Not a TPU accelerator string: {accelerator!r}. Expected e.g. '
            f"'tpu-v5p-8', 'tpu-v6e-16'.")
    gen_alias = m.group('gen').lower()
    gen = _ALIAS_TO_GEN.get(gen_alias)
    if gen is None:
        raise exceptions.InvalidAcceleratorError(
            f'Unknown TPU generation {gen_alias!r} in {accelerator!r}. Known: '
            f'{sorted(_ALIAS_TO_GEN)}')
    g = GENERATIONS[gen]
    count = int(m.group('count') or (g.small_slice_host_chips *
                                     (1 if g.suffix_counts_chips else
                                      g.cores_per_chip)))
    if not g.suffix_counts_chips and count % g.cores_per_chip:
        raise exceptions.InvalidAcceleratorError(
            f'{accelerator!r}: core count {count} must be a multiple of '
            f'{g.cores_per_chip} for {gen}.')
    num_slices = int(m.group('slices') or 1)
    if num_slices < 1:
        raise exceptions.InvalidAcceleratorError(
            f'{accelerator!r}: multislice count must be >= 1.')
    tpu = TpuType(gen, count, num_slices=num_slices)
    chips = tpu.num_chips
    if chips < g.min_chips or chips > g.max_chips:
        raise exceptions.InvalidAcceleratorError(
            f'{accelerator!r}: {chips} chips out of range '
            f'[{g.min_chips}, {g.max_chips}] for {gen}.')
    # Multi-host slice chip counts must tile exactly onto hosts; otherwise
    # the gang executor would see an inconsistent slice.
    if chips > g.small_slice_host_chips and chips % g.multi_host_chips != 0:
        raise exceptions.InvalidAcceleratorError(
            f'{accelerator!r}: multi-host slices need a multiple of '
            f'{g.multi_host_chips} chips, got {chips}.')
    if chips <= g.small_slice_host_chips and chips not in (1, 2, 4, 8):
        raise exceptions.InvalidAcceleratorError(
            f'{accelerator!r}: single-host slice sizes are 1/2/4/8 chips, '
            f'got {chips}.')
    return tpu


# A small GPU/CPU table so non-TPU controllers and mixed clusters still
# resolve (the reference keeps these in hosted CSV catalogs).
@dataclasses.dataclass(frozen=True)
class GpuSpec:
    name: str
    memory_gb: float
    bf16_tflops: float


GPUS: Dict[str, GpuSpec] = {
    'A100': GpuSpec('A100', 40, 312),
    'A100-80GB': GpuSpec('A100-80GB', 80, 312),
    'H100': GpuSpec('H100', 80, 989),
    'L4': GpuSpec('L4', 24, 121),
    'T4': GpuSpec('T4', 16, 65),
    'V100': GpuSpec('V100', 16, 112),
}


def canonicalize(accelerator: str) -> str:
    """Canonical accelerator name: TPUs normalized through parse_tpu, GPUs
    upper-cased against the GPU table."""
    if is_tpu(accelerator):
        return parse_tpu(accelerator).name
    upper = accelerator.upper()
    for name in GPUS:
        if name.upper() == upper:
            return name
    raise exceptions.InvalidAcceleratorError(
        f'Unknown accelerator {accelerator!r}. TPUs: tpu-<gen>-<size>; '
        f'GPUs: {sorted(GPUS)}')


def list_tpu_types(generation: Optional[str] = None) -> List[str]:
    """Enumerate valid slice sizes per generation (for `accelerators list`)."""
    sizes = [1, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048]
    out = []
    for g in GENERATIONS.values():
        if generation and g.name != _ALIAS_TO_GEN.get(generation, generation):
            continue
        for s in sizes:
            try:
                out.append(parse_tpu(f'tpu-{g.name}-{s}').name)
            except exceptions.InvalidAcceleratorError:
                continue
    return out
