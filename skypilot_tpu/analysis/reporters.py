"""Reporters: human text and machine-stable JSON for the analyzer.

The JSON schema is versioned and pinned by
tests/test_static_analysis.py — CI uploads the findings file as a
build artifact, so downstream tooling may parse it; bump ``version``
on any breaking shape change.
"""
from __future__ import annotations

import json
from typing import Optional

from skypilot_tpu.analysis.core import Report

JSON_SCHEMA_VERSION = 1


def render_text(report: Report, root: Optional[str] = None) -> str:
    lines = []
    for f in report.findings:
        if not f.suppressed:
            lines.append(f.format())
    n, s = len(report.unsuppressed), len(report.suppressed)
    for err in report.parse_errors:
        lines.append(f'PARSE ERROR: {err}')
    if n == 0 and not report.parse_errors:
        lines.append(
            f'skytpu check: no findings '
            f'({len(report.rules)} rules, {report.files_scanned} '
            f'files, {s} annotated exception'
            f'{"s" if s != 1 else ""}).')
    else:
        lines.append(
            f'skytpu check: {n} finding{"s" if n != 1 else ""} '
            f'({s} suppressed) across {report.files_scanned} files.')
    return '\n'.join(lines) + '\n'


def render_json(report: Report, root: Optional[str] = None) -> str:
    doc = {
        'version': JSON_SCHEMA_VERSION,
        'root': root,
        'rules': list(report.rules),
        'entry_points': list(report.entry_points),
        'findings': [
            {
                'rule': f.rule,
                'path': f.path,
                'line': f.line,
                'col': f.col,
                'message': f.message,
                'suppressed': f.suppressed,
                'reason': f.reason,
            }
            for f in report.findings
        ],
        'summary': {
            'total': len(report.unsuppressed),
            'suppressed': len(report.suppressed),
            'files_scanned': report.files_scanned,
            'parse_errors': list(report.parse_errors),
        },
    }
    return json.dumps(doc, indent=2, sort_keys=True) + '\n'
