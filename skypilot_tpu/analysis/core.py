"""Static-analysis framework for hot-path invariants.

The performance properties this codebase's benchmarks rest on — exactly
ONE device->host sync per decode step, zero mid-traffic recompiles, a
never-blocked dispatch loop, one DB access layer — are *invariants*, not
features: a single stray `np.asarray` on an untested branch silently
costs the pipelining behind the published TPOT.  Runtime tests only
guard the paths they exercise; this package makes the invariants hold
everywhere by construction:

- every ``.py`` file is parsed (never imported — analysis is pure AST,
  so deliberately-broken fixture files and heavy jax modules cost
  nothing);
- rules get per-file visitors plus an intra-package CALL GRAPH
  (callgraph.py) so "reachable from the decode loop" is a real
  reachability query, not a filename heuristic;
- intentional exceptions are annotated AT THE CALL SITE with
  ``# skytpu: allow-<token>(<reason>)`` — the reason is mandatory, so
  the exceptions are self-documenting and greppable;
- reporters (reporters.py) render text for humans and stable JSON for
  CI artifacts; the tier-1 gate (tests/test_static_analysis.py) asserts
  ZERO unsuppressed findings over skypilot_tpu/.

Entry: ``run_check(paths)`` or ``skytpu check [path]`` (client/cli.py).
"""
from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# Node types whose bodies do NOT execute as part of the enclosing
# frame (a def inside a loop/async handler defines code, it does not
# run it there) — rules walking "what executes here" stop at these.
DEF_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def iter_non_def_descendants(node: ast.AST):
    """Yield every descendant of `node` without descending into nested
    function definitions.  `node` itself is not yielded."""
    stack = [c for c in ast.iter_child_nodes(node)
             if not isinstance(c, DEF_NODES)]
    while stack:
        n = stack.pop()
        yield n
        stack.extend(c for c in ast.iter_child_nodes(n)
                     if not isinstance(c, DEF_NODES))


# ``# skytpu: allow-sync(reason)`` — also carries framework markers like
# ``# skytpu: hot-entry`` (see callgraph.py).
_SUPPRESS_RE = re.compile(
    r'#\s*skytpu:\s*allow-([a-z0-9-]+)\s*\(([^)]*)\)')
_MARKER_RE = re.compile(r'#\s*skytpu:\s*([a-z0-9-]+)\b(?!\s*\()')


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""
    rule: str
    path: str              # path as given/relative — stable across runs
    line: int
    col: int
    message: str
    suppressed: bool = False
    reason: Optional[str] = None   # the allow-annotation's reason

    def format(self) -> str:
        tag = ' (suppressed)' if self.suppressed else ''
        return (f'{self.path}:{self.line}:{self.col}: '
                f'[{self.rule}] {self.message}{tag}')


class Module:
    """One parsed source file: AST + import aliases + annotations."""

    def __init__(self, path: str, rel: str, modname: str,
                 source: str) -> None:
        self.path = path
        self.rel = rel                      # displayed / reported path
        self.modname = modname              # dotted name for callgraph
        self.source = source
        self.tree = ast.parse(source, filename=path)
        # line -> [(token, reason)] from ``# skytpu: allow-...`` comments.
        self.suppressions: Dict[int, List[Tuple[str, str]]] = {}
        # line -> [marker] from bare ``# skytpu: <marker>`` comments.
        self.markers: Dict[int, List[str]] = {}
        self._scan_comments(source)
        # alias -> dotted target ('np' -> 'numpy', 'metrics_lib' ->
        # 'skypilot_tpu.server.metrics', 'foo' -> 'pkg.mod.foo').
        self.import_aliases: Dict[str, str] = {}
        self._scan_imports()

    def _scan_comments(self, source: str) -> None:
        try:
            tokens = tokenize.generate_tokens(
                io.StringIO(source).readline)
            comments = [(t.start[0], t.string) for t in tokens
                        if t.type == tokenize.COMMENT]
        except tokenize.TokenError:
            comments = []
        for line, text in comments:
            for m in _SUPPRESS_RE.finditer(text):
                self.suppressions.setdefault(line, []).append(
                    (m.group(1), m.group(2).strip()))
            for m in _MARKER_RE.finditer(text):
                if m.group(1).startswith('allow-'):
                    continue
                self.markers.setdefault(line, []).append(m.group(1))

    def _scan_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.import_aliases[a.asname or
                                        a.name.split('.')[0]] = (
                        a.name if a.asname else a.name.split('.')[0])
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ''
                if node.level:
                    # Relative import: resolve against this module's
                    # package (one level strips the module name itself).
                    parts = self.modname.split('.')
                    parts = parts[:len(parts) - node.level]
                    base = '.'.join(parts + ([node.module]
                                             if node.module else []))
                for a in node.names:
                    if a.name == '*':
                        continue
                    self.import_aliases[a.asname or a.name] = (
                        f'{base}.{a.name}' if base else a.name)

    def suppression_for(self, node: ast.AST,
                        token: str) -> Optional[Tuple[str, str]]:
        """An ``allow-<token>`` annotation covering `node`: on any line
        the node spans, or on the line directly above it."""
        start = getattr(node, 'lineno', 0)
        end = getattr(node, 'end_lineno', start) or start
        for line in range(max(1, start - 1), end + 1):
            for tok, reason in self.suppressions.get(line, []):
                if tok == token:
                    return tok, reason
        return None

    def marker_near(self, node: ast.AST, marker: str) -> bool:
        """A bare ``# skytpu: <marker>`` on the def line (or the line
        above, for decorated defs)."""
        start = getattr(node, 'lineno', 0)
        for line in (start - 1, start):
            if marker in self.markers.get(line, []):
                return True
        return False


class Project:
    """The analyzed file set plus shared infrastructure for rules."""

    def __init__(self, modules: List[Module]) -> None:
        self.modules = modules
        self._callgraph = None

    @property
    def callgraph(self):
        if self._callgraph is None:
            from skypilot_tpu.analysis import callgraph
            self._callgraph = callgraph.CallGraph(self.modules)
        return self._callgraph

    def module_by_suffix(self, suffix: str) -> Optional[Module]:
        for m in self.modules:
            if m.path.endswith(suffix) or m.rel.endswith(suffix):
                return m
        return None

    @staticmethod
    def in_scope(module: Module, fragments: Sequence[str]) -> bool:
        """Path-fragment scoping that works for both the real package
        (root = skypilot_tpu/) and mirrored fixture trees: a fragment
        'server/' matches any path containing a /server/ component; a
        fragment ending '.py' matches by suffix."""
        path = '/' + module.path.replace(os.sep, '/').lstrip('/')
        for frag in fragments:
            if frag.endswith('.py'):
                if path.endswith('/' + frag.lstrip('/')):
                    return True
            elif f'/{frag.strip("/")}/' in path:
                return True
        return False

    def finding(self, rule, module: Module, node: ast.AST,
                message: str) -> Finding:
        """Build a Finding, applying any allow-annotation at the site.
        An annotation with an EMPTY reason does not suppress — the
        reason is the point (greppable, reviewable exceptions)."""
        sup = module.suppression_for(node, rule.suppress_token)
        if sup is not None and not sup[1]:
            message += (f' [allow-{rule.suppress_token} found but a '
                        f'reason is required: '
                        f'# skytpu: allow-{rule.suppress_token}(<why>)]')
            sup = None
        return Finding(
            rule=rule.name, path=module.rel,
            line=getattr(node, 'lineno', 0),
            col=getattr(node, 'col_offset', 0),
            message=message,
            suppressed=sup is not None,
            reason=sup[1] if sup else None)


class Rule:
    """Base class: subclasses set name/suppress_token/description and
    implement check(project) -> [Finding]."""
    name = ''
    suppress_token = ''
    description = ''

    def check(self, project: Project) -> List[Finding]:
        raise NotImplementedError


@dataclasses.dataclass
class Report:
    findings: List[Finding]
    files_scanned: int
    rules: List[str]
    entry_points: List[str]        # hot entry points the sync rule used
    parse_errors: List[str]

    @property
    def unsuppressed(self) -> List[Finding]:
        return [f for f in self.findings if not f.suppressed]

    @property
    def suppressed(self) -> List[Finding]:
        return [f for f in self.findings if f.suppressed]


def _package_root() -> str:
    import skypilot_tpu
    return os.path.dirname(os.path.abspath(skypilot_tpu.__file__))


def collect_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames
                           if d != '__pycache__' and
                           not d.startswith('.')]
            for fn in sorted(filenames):
                if fn.endswith('.py'):
                    out.append(os.path.join(dirpath, fn))
    return sorted(set(out))


def _modname_for(path: str, root: str) -> str:
    """Dotted module name: anchored at the skypilot_tpu package when the
    file lives inside it (so callgraph qualnames match the real import
    paths), else derived from the path relative to the analysis root."""
    norm = path.replace(os.sep, '/')
    marker = '/skypilot_tpu/'
    if marker in norm:
        rel = 'skypilot_tpu/' + norm.split(marker, 1)[1]
    else:
        rel = os.path.relpath(path, root).replace(os.sep, '/')
    rel = rel[:-3] if rel.endswith('.py') else rel
    if rel.endswith('/__init__'):
        rel = rel[:-len('/__init__')]
    return rel.replace('/', '.').lstrip('.')


def load_project(paths: Optional[Sequence[str]] = None
                 ) -> Tuple[Project, List[str], str]:
    """Parse the file set into a Project.  Returns (project,
    parse_errors, root)."""
    root = None
    if not paths:
        root = _package_root()
        paths = [root]
    else:
        first = os.path.abspath(paths[0])
        root = first if os.path.isdir(first) else os.path.dirname(first)
    files = collect_files(paths)
    modules: List[Module] = []
    errors: List[str] = []
    for path in files:
        rel = os.path.relpath(path, root)
        if rel.startswith('..'):
            rel = path
        try:
            with open(path, 'r', encoding='utf-8') as f:
                source = f.read()
            modules.append(
                Module(path, rel.replace(os.sep, '/'),
                       _modname_for(path, root), source))
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            errors.append(f'{rel}: {type(e).__name__}: {e}')
    return Project(modules), errors, root


def run_check(paths: Optional[Sequence[str]] = None,
              rules: Optional[Iterable[str]] = None) -> Report:
    """Run the (optionally filtered) rule set over `paths` (default:
    the installed skypilot_tpu package)."""
    from skypilot_tpu.analysis.rules import all_rules
    active = all_rules()
    if rules:
        wanted = set(rules)
        unknown = wanted - {r.name for r in active}
        if unknown:
            raise ValueError(
                f'unknown rule(s): {sorted(unknown)}; known: '
                f'{sorted(r.name for r in active)}')
        active = [r for r in active if r.name in wanted]
    project, errors, _ = load_project(paths)
    findings: List[Finding] = []
    entry_points: List[str] = []
    for rule in active:
        findings.extend(rule.check(project))
        entry_points.extend(getattr(rule, 'entry_points_used', []))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return Report(findings=findings, files_scanned=len(project.modules),
                  rules=[r.name for r in active],
                  entry_points=sorted(set(entry_points)),
                  parse_errors=errors)
