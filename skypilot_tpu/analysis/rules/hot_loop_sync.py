"""hot-loop-sync: no device->host sync on the hot loops.

The decode engine's contract is exactly ONE sync per step (the
``np.asarray(out)`` fetch); the trainer's dispatch runs ahead of the
device and is throttled only by donated buffers.  Any additional
``np.asarray`` / ``.item()`` / ``block_until_ready`` /
``jax.device_get`` / ``float(jax-value)`` in a function reachable from
those loops serializes host and device — the exact stall that caps TPU
scaling (arXiv:2011.03641) and blows the TPOT the serve SLOs schedule
against.  Intentional sync points carry
``# skytpu: allow-sync(<reason>)`` at the call site.

Entry points: functions marked ``# skytpu: hot-entry`` plus the known
engine/trainer/RL loops as hardcoded backstops.  Jit-wrapped functions
are skipped (their bodies trace once; host ops there are a trace-time
constant, not a per-step sync).
"""
from __future__ import annotations

import ast
from typing import List, Optional

from skypilot_tpu.analysis import callgraph as cg
from skypilot_tpu.analysis.core import Finding, Project, Rule

# Backstop entry points (qualname suffixes) — the marker comment in the
# source is the primary mechanism; these keep the gate honest even if a
# marker is dropped.
DEFAULT_ENTRY_POINTS = (
    'skypilot_tpu.inference.engine.DecodeEngine.step',
    'skypilot_tpu.inference.engine.DecodeEngine.step_pipelined',
    'skypilot_tpu.inference.engine.DecodeEngine._loop',
    'skypilot_tpu.inference.engine.DecodeEngine.drain',
    'skypilot_tpu.train.trainer.Trainer.run',
    'skypilot_tpu.train.rl.rollout',
)

# numpy entry points that materialize device arrays on the host.
_NUMPY_SYNCS = ('asarray', 'array', 'copy')
_SYNC_METHODS = ('item', 'tolist', 'block_until_ready')


def _jaxish(node: ast.AST, module) -> bool:
    """Does the expression mention a jax-aliased name (jnp./jax.)?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            resolved = cg.resolve_alias(sub.id, module)
            if resolved == 'jax' or resolved.startswith('jax.'):
                return True
    return False


class HotLoopSyncRule(Rule):
    name = 'hot-loop-sync'
    suppress_token = 'sync'
    description = ('device->host syncs (np.asarray/.item()/device_get/'
                   'block_until_ready/float-on-Array) reachable from '
                   'the decode loop / train step / RL rollout')

    def __init__(self) -> None:
        self.entry_points_used: List[str] = []

    def check(self, project: Project) -> List[Finding]:
        graph = project.callgraph
        entries = graph.entry_points(defaults=DEFAULT_ENTRY_POINTS)
        self.entry_points_used = entries
        reachable = graph.reachable_from(entries)
        findings: List[Finding] = []
        for qual in sorted(reachable):
            info = graph.functions[qual]
            if info.jit_wrapped:
                continue
            module = info.module
            for call in info.calls:
                msg = self._sync_message(call, module)
                if msg is not None:
                    findings.append(project.finding(
                        self, module, call,
                        f'{msg} in {qual} (reachable from hot entry '
                        f'point{"s" if len(entries) > 1 else ""}) — '
                        f'device->host sync on a hot loop'))
        return findings

    def _sync_message(self, call: ast.Call,
                      module) -> Optional[str]:
        func = call.func
        dotted = cg._dotted(func)
        if dotted is not None:
            resolved = cg.resolve_alias(dotted, module)
            if resolved == 'jax.device_get':
                return 'jax.device_get(...)'
            head, _, tail = resolved.partition('.')
            if head == 'numpy' and tail in _NUMPY_SYNCS:
                return f'np.{tail}(...)'
        if isinstance(func, ast.Attribute) and \
                func.attr in _SYNC_METHODS and not call.args:
            return f'.{func.attr}()'
        if isinstance(func, ast.Name) and func.id in ('float', 'int') \
                and len(call.args) == 1 and \
                _jaxish(call.args[0], module):
            return f'{func.id}(<jax value>)'
        return None
