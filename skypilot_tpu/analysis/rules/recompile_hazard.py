"""recompile-hazard: zero mid-traffic XLA recompiles.

Two hazards:

1. ``jax.jit(...)`` lexically inside a ``for``/``while`` loop — a fresh
   jit wrapper per iteration defeats the compilation cache (each
   wrapper has its own identity) and risks a multi-second compile on a
   per-request path.  Anywhere in the package.

2. A jitted callable in a HOT module (the decode engine, trainer, RL
   step) with neither pinned ``in_shardings``/``out_shardings`` nor
   ``donate_argnums``: unpinned programs recompile when an input's
   placement drifts, and undonated state doubles HBM and breaks the
   call-k+1-reuses-call-k's-buffers invariant the zero-recompile tests
   assert.  Intentional one-shot compiles carry
   ``# skytpu: allow-recompile(<reason>)``.
"""
from __future__ import annotations

import ast
from typing import List

from skypilot_tpu.analysis import callgraph as cg
from skypilot_tpu.analysis.core import (Finding, Project, Rule,
                                        iter_non_def_descendants)

_HOT_MODULES = ('inference/engine.py', 'train/trainer.py',
                'train/rl.py', 'inference/weights.py')
_PIN_KWARGS = ('in_shardings', 'out_shardings', 'donate_argnums',
               'donate_argnames')


class RecompileHazardRule(Rule):
    name = 'recompile-hazard'
    suppress_token = 'recompile'
    description = ('jax.jit inside loops; hot-path jit without pinned '
                   'shardings or donated state')

    def check(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for module in project.modules:
            hot = any(module.path.endswith(m) or module.rel.endswith(m)
                      for m in _HOT_MODULES)
            # Dedupe across nested loops: a jit inside `for: while:` is
            # seen from both enclosing loops but is ONE finding.
            seen = set()
            for node in ast.walk(module.tree):
                if isinstance(node, (ast.For, ast.While,
                                     ast.AsyncFor)):
                    for f in self._jits_in_loop(project, module, node):
                        if (f.line, f.col) not in seen:
                            seen.add((f.line, f.col))
                            findings.append(f)
            if not hot:
                continue
            for node in ast.walk(module.tree):
                if isinstance(node, ast.Call) and \
                        cg.is_jit_call(node, module) and \
                        not self._pinned(node):
                    findings.append(project.finding(
                        self, module, node,
                        'jitted hot-path callable without pinned '
                        'in/out shardings or donated state — input '
                        'placement drift recompiles mid-traffic and '
                        'undonated buffers double HBM'))
        return findings

    def _jits_in_loop(self, project: Project, module,
                      loop) -> List[Finding]:
        out = []
        for node in iter_non_def_descendants(loop):
            if isinstance(node, ast.Call) and \
                    cg.is_jit_call(node, module):
                out.append(project.finding(
                    self, module, node,
                    'jax.jit(...) inside a loop — a fresh wrapper '
                    'per iteration defeats the compile cache '
                    '(recompile on a per-request path)'))
        return out

    @staticmethod
    def _pinned(call: ast.Call) -> bool:
        return any(kw.arg in _PIN_KWARGS for kw in call.keywords)
