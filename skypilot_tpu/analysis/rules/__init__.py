"""Rule registry: one instance of every invariant rule.

Adding a rule = adding a module here and registering it; the tier-1
gate (tests/test_static_analysis.py) requires every registered rule to
have at least one known-bad fixture proving it fires.
"""
from __future__ import annotations

from typing import List

from skypilot_tpu.analysis.core import Rule
from skypilot_tpu.analysis.rules.blocking_async import BlockingAsyncRule
from skypilot_tpu.analysis.rules.db_discipline import DbDisciplineRule
from skypilot_tpu.analysis.rules.hot_loop_sync import HotLoopSyncRule
from skypilot_tpu.analysis.rules.metric_naming import MetricNamingRule
from skypilot_tpu.analysis.rules.recompile_hazard import (
    RecompileHazardRule)
from skypilot_tpu.analysis.rules.speculation import SpeculationRule
from skypilot_tpu.analysis.rules.unbounded_io import UnboundedIoRule


def all_rules() -> List[Rule]:
    return [
        HotLoopSyncRule(),
        RecompileHazardRule(),
        BlockingAsyncRule(),
        DbDisciplineRule(),
        UnboundedIoRule(),
        MetricNamingRule(),
        SpeculationRule(),
    ]
