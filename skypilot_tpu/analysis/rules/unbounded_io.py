"""unbounded-io: every outbound call in control-plane code is bounded.

Provisioning, controllers and recovery paths talk to cloud APIs and
remote hosts; a hung TCP connection with no timeout wedges a
controller tick (and with it every service/job that controller owns)
forever.  Three checks over the control-plane scope:

1. ``requests.<verb>(...)`` (and ``*session*.<verb>(...)``) without a
   ``timeout=`` kwarg;
2. ``subprocess.run/check_output/check_call/call(...)`` without
   ``timeout=`` (``Popen`` is exempt: it does not block by itself and
   its ``wait``/pumps carry their own deadlines);
3. ``while True:`` retry loops that make a network call with neither a
   sleep/backoff nor a deadline (``time.time``/``time.monotonic``)
   anywhere in the body — the hot-spin/no-bound retry shape.

Bulk data transfers (rsync / gsutil / aws s3) are bounded by data
size, not wall time — those sites carry
``# skytpu: allow-unbounded-io(<reason>)``.
"""
from __future__ import annotations

import ast
from typing import List, Optional

from skypilot_tpu.analysis import callgraph as cg
from skypilot_tpu.analysis.core import (Finding, Project, Rule,
                                        iter_non_def_descendants)

_SCOPE = ('provision/', 'jobs/', 'clouds/', 'backends/', 'data/',
          'serve/', 'agent/', 'catalog/', 'authentication.py',
          'controller_vm.py', 'utils/command_runner.py',
          # Disaggregated serving: the KV-handoff push client and the
          # inference server's prefill->decode relay are data-plane
          # HTTP — a handoff with no deadline wedges the REQUEST (and
          # its decode slot reservation) forever, exactly the failure
          # this rule exists for.
          'inference/',
          # The fleet simulator drives the real control plane in a
          # tight tick loop — an unpaced retry or a deadline-less call
          # there turns a 240 s simulated day into a hung process.
          'fleetsim/')
_REQUESTS_VERBS = ('get', 'post', 'put', 'delete', 'head', 'patch',
                   'request')
_SUBPROCESS_BLOCKING = ('run', 'check_output', 'check_call', 'call')
_SLEEPY = ('sleep', 'wait', 'backoff')


class UnboundedIoRule(Rule):
    name = 'unbounded-io'
    suppress_token = 'unbounded-io'
    description = ('requests/subprocess without timeout, and '
                   'while-True retry loops with no backoff/deadline, '
                   'in provisioning/controller paths')

    def check(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for module in project.modules:
            if not Project.in_scope(module, _SCOPE):
                continue
            for node in ast.walk(module.tree):
                if isinstance(node, ast.Call):
                    msg = self._unbounded_call(node, module)
                    if msg is not None:
                        findings.append(project.finding(
                            self, module, node, msg))
                elif isinstance(node, ast.While) and \
                        self._is_while_true(node):
                    msg = self._unbounded_retry(node, module)
                    if msg is not None:
                        findings.append(project.finding(
                            self, module, node, msg))
        return findings

    # ----- calls -------------------------------------------------------------
    def _unbounded_call(self, call: ast.Call,
                        module) -> Optional[str]:
        if any(kw.arg == 'timeout' for kw in call.keywords):
            return None
        dotted = cg._dotted(call.func)
        if dotted is None:
            return None
        resolved = cg.resolve_alias(dotted, module)
        head, _, tail = resolved.partition('.')
        if head == 'requests' and tail in _REQUESTS_VERBS:
            return (f'requests.{tail}(...) without timeout= — a hung '
                    f'connection wedges this control-plane path '
                    f'forever')
        if head == 'subprocess' and tail in _SUBPROCESS_BLOCKING:
            return (f'subprocess.{tail}(...) without timeout= — a '
                    f'hung child blocks the controller tick forever')
        # session.get/post/... on anything *session*-named.
        if isinstance(call.func, ast.Attribute) and \
                call.func.attr in _REQUESTS_VERBS:
            base = cg._dotted(call.func.value) or ''
            if 'session' in base.split('.')[-1].lower():
                return (f'{base}.{call.func.attr}(...) without '
                        f'timeout= — HTTP session call can hang '
                        f'forever')
        return None

    # ----- retry loops -------------------------------------------------------
    @staticmethod
    def _is_while_true(node: ast.While) -> bool:
        test = node.test
        return isinstance(test, ast.Constant) and test.value is True

    def _unbounded_retry(self, loop: ast.While,
                         module) -> Optional[str]:
        has_net = False
        has_pacing = False
        for node in iter_non_def_descendants(loop):
            if isinstance(node, ast.Call):
                dotted = cg._dotted(node.func) or ''
                resolved = cg.resolve_alias(dotted, module)
                head = resolved.partition('.')[0]
                last = resolved.split('.')[-1]
                if head in ('requests', 'subprocess') or \
                        last in ('request', '_request') or \
                        'session' in (dotted.split('.')[-2:-1] or
                                      [''])[0].lower():
                    has_net = True
                if any(s in last.lower() for s in _SLEEPY):
                    has_pacing = True
                if resolved in ('time.time', 'time.monotonic',
                                'time.perf_counter'):
                    has_pacing = True
        if has_net and not has_pacing:
            return ('while True retry loop with a network call but no '
                    'backoff/sleep and no deadline '
                    '(time.time/monotonic) — unbounded hot retry')
        return None
