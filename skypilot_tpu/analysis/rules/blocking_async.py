"""blocking-in-async: the event loops must never block.

The API server, the serve load balancer, the node agent and the
inference server are single-event-loop aiohttp apps: one synchronous
``time.sleep`` / ``requests.*`` / ``subprocess.*`` / sqlite call inside
an ``async def`` stalls EVERY in-flight request on that loop — on the
LB that is a head-of-line block for all replicas at once.  Blocking
work belongs on a thread (``loop.run_in_executor``) or in the executor
worker processes.  ``asyncio.sleep`` and aiohttp calls are of course
fine (awaited).  Annotate deliberate exceptions with
``# skytpu: allow-blocking(<reason>)``.
"""
from __future__ import annotations

import ast
from typing import List, Optional

from skypilot_tpu.analysis import callgraph as cg
from skypilot_tpu.analysis.core import (Finding, Project, Rule,
                                        iter_non_def_descendants)

_SCOPE = ('server/', 'serve/load_balancer.py', 'agent/',
          'inference/server.py')
_SUBPROCESS_FNS = ('run', 'check_output', 'check_call', 'call',
                   'Popen', 'getoutput', 'getstatusoutput')
_REQUESTS_FNS = ('get', 'post', 'put', 'delete', 'head', 'patch',
                 'request', 'Session')


class BlockingAsyncRule(Rule):
    name = 'blocking-in-async'
    suppress_token = 'blocking'
    description = ('time.sleep / requests.* / subprocess.* / sqlite '
                   'inside async def in the server, LB and agent '
                   'event loops')

    def check(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for module in project.modules:
            if not Project.in_scope(module, _SCOPE):
                continue
            for node in ast.walk(module.tree):
                if isinstance(node, ast.AsyncFunctionDef):
                    findings.extend(
                        self._check_async(project, module, node))
        return findings

    def _check_async(self, project: Project, module,
                     fn: ast.AsyncFunctionDef) -> List[Finding]:
        out = []
        for node in iter_non_def_descendants(fn):
            if not isinstance(node, ast.Call):
                continue
            what = self._blocking_call(node, module)
            if what is not None:
                out.append(project.finding(
                    self, module, node,
                    f'{what} inside async def {fn.name} — blocks the '
                    f'event loop (every in-flight request on it); '
                    f'use asyncio.sleep / run_in_executor'))
        return out

    def _blocking_call(self, call: ast.Call,
                       module) -> Optional[str]:
        dotted = cg._dotted(call.func)
        if dotted is None:
            return None
        resolved = cg.resolve_alias(dotted, module)
        head, _, tail = resolved.partition('.')
        if resolved == 'time.sleep':
            return 'time.sleep(...)'
        if head == 'requests' and tail in _REQUESTS_FNS:
            return f'requests.{tail}(...)'
        if head == 'subprocess' and tail in _SUBPROCESS_FNS:
            return f'subprocess.{tail}(...)'
        if head == 'sqlite3' or resolved.startswith(
                'skypilot_tpu.utils.db_utils.'):
            return f'{resolved}(...) (synchronous sqlite)'
        return None
