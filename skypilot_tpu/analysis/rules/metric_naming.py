"""metric-naming: registry discipline for every exported family AND
every flight-recorder span.

server/metrics.py renders the Prometheus exposition format itself and
the LB federates it across replicas — so naming is a cross-process
contract: consumers (SLO autoscaler, admission control, dashboards)
find series by name.  tests/test_observability.py asserts the
conventions dynamically for call sites the tests happen to execute;
this rule asserts them for EVERY call site statically:

- the family name is a legal Prometheus metric name;
- it has a ``_HELP`` entry in server/metrics.py (central registry);
- counters end ``_total``; gauges must NOT end ``_total``;
  histogram/summary families end ``_seconds``/``_bytes``/``_ratio``;
- device-cost attribution suffixes (``_mfu``/``_per_token``/
  ``_intensity`` — the perf/cost_model.py families) are gauge-only:
  they name instantaneous modeled quantities, and exporting one as a
  counter or histogram misleads every roofline consumer downstream.

The flight recorder's span names (server/tracing.py) are the same kind
of cross-process contract — the LB federates /debug views by span name
and `skytpu trace`'s decomposition keys on them — so every
``record_span``/``record_instant`` call site is held to the same bar:

- the span name is legal (dotted lowercase, ``component.event``);
- it has a ``SPAN_HELP`` entry in server/tracing.py.

SLO alert rules (obs/alerts.py) are consumers on the far END of that
contract: an ``AlertRule`` naming a family nobody registers would
never fire and never error — the worst observability failure mode.  So
every statically-visible ``AlertRule(...)`` construction's ``family=``
/ ``ratio_family=`` keyword must resolve to a ``_HELP``-registered
family.

Names are resolved statically: string literals, module-level string
constants, and ``metrics_lib.<CONST>`` attributes (parsed out of
server/metrics.py — nothing is imported).  Dynamically-built names are
skipped (and are themselves a smell worth avoiding).
"""
from __future__ import annotations

import ast
import importlib.util
import re
from typing import Dict, List, Optional

from skypilot_tpu.analysis import callgraph as cg
from skypilot_tpu.analysis.core import Finding, Module, Project, Rule

_METRICS_MODULE = 'skypilot_tpu.server.metrics'
_TRACING_MODULE = 'skypilot_tpu.server.tracing'
_ALERTS_MODULE = 'skypilot_tpu.obs.alerts'
# AlertRule keywords that must name a registered metric family.
_ALERT_FAMILY_KWARGS = ('family', 'ratio_family')
_NAME_RE = re.compile(r'^[a-zA-Z_:][a-zA-Z0-9_:]*$')
# Span names: dotted lowercase, component.event.
_SPAN_NAME_RE = re.compile(r'^[a-z][a-z0-9_]*(\.[a-z][a-z0-9_]*)+$')
# registration fn -> instrument kind
_KINDS = {
    'inc_counter': 'counter',
    'set_gauge': 'gauge',
    'add_gauge': 'gauge',
    'remove_gauge': 'gauge',
    'observe': 'summary',
    'observe_hist': 'histogram',
}
# Flight-recorder registration fns (span name = 2nd positional arg).
_SPAN_FNS = ('record_span', 'record_instant')
# Device-cost attribution suffixes (perf/cost_model.py): instantaneous
# modeled ratios, legal only as gauges — see module docstring.
_GAUGE_ONLY_SUFFIXES = ('_mfu', '_per_token', '_intensity')


def _module_constants(tree: ast.AST) -> Dict[str, str]:
    """Module-level NAME = 'literal' assignments."""
    out: Dict[str, str] = {}
    for node in ast.iter_child_nodes(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            out[node.targets[0].id] = node.value.value
    return out


def _dict_keys(tree: ast.AST, var_name: str) -> Optional[set]:
    """String keys of a module-level ``var_name = {...}`` dict literal
    (the _HELP registry in server/metrics.py, SPAN_HELP in
    server/tracing.py)."""
    for node in ast.iter_child_nodes(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == var_name and \
                isinstance(node.value, ast.Dict):
            keys = set()
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and \
                        isinstance(k.value, str):
                    keys.add(k.value)
            return keys
    return None


def _load_module_ast(module_name: str) -> Optional[ast.AST]:
    """Parse an installed module's source (never imported)."""
    try:
        spec = importlib.util.find_spec(module_name)
        if spec is None or not spec.origin:
            return None
        with open(spec.origin, 'r', encoding='utf-8') as f:
            return ast.parse(f.read(), filename=spec.origin)
    except (OSError, SyntaxError, ImportError, ValueError):
        return None


class MetricNamingRule(Rule):
    name = 'metric-naming'
    suppress_token = 'metric-naming'
    description = ('registered metric families must satisfy the '
                   'exposition-format conventions and have a _HELP '
                   'entry in server/metrics.py; flight-recorder spans '
                   'must be legal dotted names with a SPAN_HELP entry '
                   'in server/tracing.py')

    def check(self, project: Project) -> List[Finding]:
        # Prefer the metrics/tracing modules from the analyzed set (so
        # a fixture tree can ship its own); fall back to the installed
        # ones for fixture files that register against the real
        # registries.
        metrics_mod = project.module_by_suffix('server/metrics.py')
        metrics_tree = metrics_mod.tree if metrics_mod else \
            _load_module_ast(_METRICS_MODULE)
        help_keys = _dict_keys(metrics_tree, '_HELP') \
            if metrics_tree else None
        metrics_consts = (_module_constants(metrics_tree)
                          if metrics_tree else {})
        tracing_mod = project.module_by_suffix('server/tracing.py')
        tracing_tree = tracing_mod.tree if tracing_mod else \
            _load_module_ast(_TRACING_MODULE)
        span_keys = _dict_keys(tracing_tree, 'SPAN_HELP') \
            if tracing_tree else None
        findings: List[Finding] = []
        for module in project.modules:
            consts = _module_constants(module.tree)
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call):
                    continue
                kind = self._registration_kind(node, module)
                if kind is not None:
                    name = self._static_name(node, module, consts,
                                             metrics_consts, arg_idx=0)
                    if name is None:
                        continue  # dynamic name: out of static reach
                    findings.extend(self._check_name(
                        project, module, node, kind, name, help_keys))
                    continue
                if self._is_span_registration(node, module):
                    name = self._static_name(node, module, consts,
                                             metrics_consts, arg_idx=1)
                    if name is None:
                        continue
                    findings.extend(self._check_span_name(
                        project, module, node, name, span_keys))
                    continue
                if self._is_alert_rule(node, module):
                    findings.extend(self._check_alert_rule(
                        project, module, node, consts, metrics_consts,
                        help_keys))
        return findings

    def _registration_kind(self, call: ast.Call,
                           module: Module) -> Optional[str]:
        dotted = cg._dotted(call.func)
        if dotted is None:
            return None
        resolved = cg.resolve_alias(dotted, module)
        last = resolved.split('.')[-1]
        if last not in _KINDS:
            return None
        # Only calls that resolve into the metrics module (via module
        # alias or from-import) — an unrelated local `observe` is not
        # a metric registration.
        if resolved == f'{_METRICS_MODULE}.{last}':
            return _KINDS[last]
        return None

    def _is_span_registration(self, call: ast.Call,
                              module: Module) -> bool:
        dotted = cg._dotted(call.func)
        if dotted is None:
            return False
        resolved = cg.resolve_alias(dotted, module)
        last = resolved.split('.')[-1]
        return last in _SPAN_FNS and \
            resolved == f'{_TRACING_MODULE}.{last}'

    def _is_alert_rule(self, call: ast.Call, module: Module) -> bool:
        dotted = cg._dotted(call.func)
        if dotted is None:
            return False
        resolved = cg.resolve_alias(dotted, module)
        return resolved == f'{_ALERTS_MODULE}.AlertRule'

    def _check_alert_rule(self, project: Project, module: Module,
                          call: ast.Call, consts: Dict[str, str],
                          metrics_consts: Dict[str, str],
                          help_keys) -> List[Finding]:
        """Every statically-resolvable family reference in an AlertRule
        must be a registered family — a rule watching an unregistered
        name silently never fires (dynamically-built values are out of
        static reach, same posture as registration names)."""
        out: List[Finding] = []
        if help_keys is None:
            return out
        for kw in call.keywords:
            if kw.arg not in _ALERT_FAMILY_KWARGS:
                continue
            name = self._static_value(kw.value, module, consts,
                                      metrics_consts)
            if name is None or not name:
                continue
            if name not in help_keys:
                out.append(project.finding(
                    self, module, call,
                    f'AlertRule {kw.arg}={name!r} references a family '
                    f'with no _HELP entry in server/metrics.py — an '
                    f'alert rule on an unregistered family can never '
                    f'fire'))
        return out

    def _static_name(self, call: ast.Call, module: Module,
                     consts: Dict[str, str],
                     metrics_consts: Dict[str, str],
                     arg_idx: int = 0) -> Optional[str]:
        if len(call.args) <= arg_idx:
            return None
        return self._static_value(call.args[arg_idx], module, consts,
                                  metrics_consts)

    def _static_value(self, arg: ast.expr, module: Module,
                      consts: Dict[str, str],
                      metrics_consts: Dict[str, str]) -> Optional[str]:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value
        if isinstance(arg, ast.Name):
            return consts.get(arg.id)
        if isinstance(arg, ast.Attribute) and \
                isinstance(arg.value, ast.Name):
            base = cg.resolve_alias(arg.value.id, module)
            if base == _METRICS_MODULE:
                return metrics_consts.get(arg.attr)
        return None

    def _check_span_name(self, project: Project, module: Module,
                         node: ast.Call, name: str,
                         span_keys) -> List[Finding]:
        out = []
        if not _SPAN_NAME_RE.match(name):
            out.append(project.finding(
                self, module, node,
                f'{name!r} is not a legal span name (dotted lowercase '
                f'component.event, e.g. engine.queue_wait)'))
            return out
        if span_keys is not None and name not in span_keys:
            out.append(project.finding(
                self, module, node,
                f'span {name!r} has no SPAN_HELP entry in '
                f'server/tracing.py — every recorded span is '
                f'documented centrally (federation and skytpu trace '
                f'key on these names)'))
        return out

    def _check_name(self, project: Project, module: Module,
                    node: ast.Call, kind: str, name: str,
                    help_keys) -> List[Finding]:
        out = []
        if not _NAME_RE.match(name):
            out.append(project.finding(
                self, module, node,
                f'metric name {name!r} is not a legal Prometheus '
                f'metric name'))
            return out
        if kind == 'counter' and not name.endswith('_total'):
            out.append(project.finding(
                self, module, node,
                f'counter {name!r} must end _total (exposition '
                f'convention; federation consumers rely on it)'))
        if kind == 'gauge' and name.endswith('_total'):
            out.append(project.finding(
                self, module, node,
                f'gauge {name!r} must not end _total (that suffix '
                f'promises a monotonic counter)'))
        if kind in ('histogram', 'summary') and not name.endswith(
                ('_seconds', '_bytes', '_ratio')):
            out.append(project.finding(
                self, module, node,
                f'{kind} {name!r} must carry a unit suffix '
                f'(_seconds/_bytes/_ratio)'))
        if kind != 'gauge' and name.endswith(_GAUGE_ONLY_SUFFIXES):
            out.append(project.finding(
                self, module, node,
                f'{kind} {name!r} carries a device-cost attribution '
                f'suffix ({"/".join(_GAUGE_ONLY_SUFFIXES)}) — these '
                f'are instantaneous modeled quantities, legal only '
                f'as gauges'))
        if help_keys is not None and name not in help_keys:
            out.append(project.finding(
                self, module, node,
                f'{name!r} has no _HELP entry in server/metrics.py — '
                f'every exported family is documented centrally'))
        return out
