"""metric-naming: registry discipline for every exported family.

server/metrics.py renders the Prometheus exposition format itself and
the LB federates it across replicas — so naming is a cross-process
contract: consumers (SLO autoscaler, admission control, dashboards)
find series by name.  tests/test_observability.py asserts the
conventions dynamically for call sites the tests happen to execute;
this rule asserts them for EVERY call site statically:

- the family name is a legal Prometheus metric name;
- it has a ``_HELP`` entry in server/metrics.py (central registry);
- counters end ``_total``; gauges must NOT end ``_total``;
  histogram/summary families end ``_seconds``/``_bytes``/``_ratio``.

Names are resolved statically: string literals, module-level string
constants, and ``metrics_lib.<CONST>`` attributes (parsed out of
server/metrics.py — nothing is imported).  Dynamically-built names are
skipped (and are themselves a smell worth avoiding).
"""
from __future__ import annotations

import ast
import importlib.util
import re
from typing import Dict, List, Optional

from skypilot_tpu.analysis import callgraph as cg
from skypilot_tpu.analysis.core import Finding, Module, Project, Rule

_METRICS_MODULE = 'skypilot_tpu.server.metrics'
_NAME_RE = re.compile(r'^[a-zA-Z_:][a-zA-Z0-9_:]*$')
# registration fn -> instrument kind
_KINDS = {
    'inc_counter': 'counter',
    'set_gauge': 'gauge',
    'add_gauge': 'gauge',
    'remove_gauge': 'gauge',
    'observe': 'summary',
    'observe_hist': 'histogram',
}


def _module_constants(tree: ast.AST) -> Dict[str, str]:
    """Module-level NAME = 'literal' assignments."""
    out: Dict[str, str] = {}
    for node in ast.iter_child_nodes(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            out[node.targets[0].id] = node.value.value
    return out


def _help_keys(tree: ast.AST) -> Optional[set]:
    """Keys of the _HELP dict literal in server/metrics.py."""
    for node in ast.iter_child_nodes(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == '_HELP' and \
                isinstance(node.value, ast.Dict):
            keys = set()
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and \
                        isinstance(k.value, str):
                    keys.add(k.value)
            return keys
    return None


def _load_metrics_ast() -> Optional[ast.AST]:
    """Parse the installed server/metrics.py (never imported)."""
    try:
        spec = importlib.util.find_spec(_METRICS_MODULE)
        if spec is None or not spec.origin:
            return None
        with open(spec.origin, 'r', encoding='utf-8') as f:
            return ast.parse(f.read(), filename=spec.origin)
    except (OSError, SyntaxError, ImportError, ValueError):
        return None


class MetricNamingRule(Rule):
    name = 'metric-naming'
    suppress_token = 'metric-naming'
    description = ('registered metric families must satisfy the '
                   'exposition-format conventions and have a _HELP '
                   'entry in server/metrics.py')

    def check(self, project: Project) -> List[Finding]:
        # Prefer the metrics module from the analyzed set (so a
        # fixture tree can ship its own); fall back to the installed
        # one for fixture files that register against the real
        # registry.
        metrics_mod = project.module_by_suffix('server/metrics.py')
        metrics_tree = metrics_mod.tree if metrics_mod else \
            _load_metrics_ast()
        help_keys = _help_keys(metrics_tree) if metrics_tree else None
        metrics_consts = (_module_constants(metrics_tree)
                          if metrics_tree else {})
        findings: List[Finding] = []
        for module in project.modules:
            consts = _module_constants(module.tree)
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call):
                    continue
                kind = self._registration_kind(node, module)
                if kind is None:
                    continue
                name = self._static_name(node, module, consts,
                                         metrics_consts)
                if name is None:
                    continue      # dynamic name: out of static reach
                findings.extend(self._check_name(
                    project, module, node, kind, name, help_keys))
        return findings

    def _registration_kind(self, call: ast.Call,
                           module: Module) -> Optional[str]:
        dotted = cg._dotted(call.func)
        if dotted is None:
            return None
        resolved = cg.resolve_alias(dotted, module)
        last = resolved.split('.')[-1]
        if last not in _KINDS:
            return None
        # Only calls that resolve into the metrics module (via module
        # alias or from-import) — an unrelated local `observe` is not
        # a metric registration.
        if resolved == f'{_METRICS_MODULE}.{last}':
            return _KINDS[last]
        return None

    def _static_name(self, call: ast.Call, module: Module,
                     consts: Dict[str, str],
                     metrics_consts: Dict[str, str]) -> Optional[str]:
        if not call.args:
            return None
        arg = call.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value
        if isinstance(arg, ast.Name):
            return consts.get(arg.id)
        if isinstance(arg, ast.Attribute) and \
                isinstance(arg.value, ast.Name):
            base = cg.resolve_alias(arg.value.id, module)
            if base == _METRICS_MODULE:
                return metrics_consts.get(arg.attr)
        return None

    def _check_name(self, project: Project, module: Module,
                    node: ast.Call, kind: str, name: str,
                    help_keys) -> List[Finding]:
        out = []
        if not _NAME_RE.match(name):
            out.append(project.finding(
                self, module, node,
                f'metric name {name!r} is not a legal Prometheus '
                f'metric name'))
            return out
        if kind == 'counter' and not name.endswith('_total'):
            out.append(project.finding(
                self, module, node,
                f'counter {name!r} must end _total (exposition '
                f'convention; federation consumers rely on it)'))
        if kind == 'gauge' and name.endswith('_total'):
            out.append(project.finding(
                self, module, node,
                f'gauge {name!r} must not end _total (that suffix '
                f'promises a monotonic counter)'))
        if kind in ('histogram', 'summary') and not name.endswith(
                ('_seconds', '_bytes', '_ratio')):
            out.append(project.finding(
                self, module, node,
                f'{kind} {name!r} must carry a unit suffix '
                f'(_seconds/_bytes/_ratio)'))
        if help_keys is not None and name not in help_keys:
            out.append(project.finding(
                self, module, node,
                f'{name!r} has no _HELP entry in server/metrics.py — '
                f'every exported family is documented centrally'))
        return out
