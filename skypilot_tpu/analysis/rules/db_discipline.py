"""db-discipline: ONE database access layer.

The state-backend subsystem (skypilot_tpu/state/: sqlite + Postgres
selected by DSN) swaps cleanly precisely because every connection in
the tree flows through the ``utils/db_utils.py`` funnel — a stray
``sqlite3.connect`` (or a stray ``psycopg.connect``) anywhere else is
a silent second source of truth that the other backend will not see,
and that the lease/claim protocol cannot protect.  This rule pins the
funnel: holding the ``sqlite3`` **or** ``psycopg`` import at all is
only legal in the backend implementations under ``state/`` (plus the
funnel itself and the state modules written against it).
"""
from __future__ import annotations

import ast
from typing import List

from skypilot_tpu.analysis import callgraph as cg
from skypilot_tpu.analysis.core import Finding, Project, Rule

# The funnel + the backends behind it + the state modules above it.
ALLOWED_FILES = (
    'utils/db_utils.py',          # the op-set funnel itself
    'state/__init__.py',          # backend selection (DSN dispatch)
    'state/sqlite.py',            # sqlite backend (holds sqlite3)
    'state/postgres.py',          # Postgres backend (holds psycopg)
    'state/dialect.py',           # SQL translation (no connections)
    'state/leases.py',            # heartbeat leases (via db_utils)
    'global_user_state.py',       # cluster/user state
    'jobs/state.py',              # managed-jobs state
    'serve/serve_state.py',       # serve services/replicas
    'server/requests_db.py',      # API request records
)

# Driver modules whose import anywhere else breaks the funnel.
_DB_MODULES = ('sqlite3', 'psycopg', 'psycopg2')


class DbDisciplineRule(Rule):
    name = 'db-discipline'
    suppress_token = 'db'
    description = ('direct sqlite3/psycopg use outside the state-store '
                   'funnel (utils/db_utils.py + skypilot_tpu/state/ '
                   'backends + the state modules)')

    def check(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for module in project.modules:
            if any(module.path.endswith(a) or module.rel.endswith(a)
                   for a in ALLOWED_FILES):
                continue
            for node in ast.walk(module.tree):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        if a.name.split('.')[0] in _DB_MODULES:
                            findings.append(project.finding(
                                self, module, node,
                                f'import {a.name.split(".")[0]} outside '
                                f'the DB access layer — all connections '
                                f'must flow through utils/db_utils.py '
                                f'(the funnel the state backends live '
                                f'behind)'))
                elif isinstance(node, ast.ImportFrom):
                    root = (node.module or '').split('.')[0]
                    if root in _DB_MODULES:
                        findings.append(project.finding(
                            self, module, node,
                            f'from {root} import ... outside the DB '
                            f'access layer — use utils/db_utils.py'))
                elif isinstance(node, ast.Call):
                    dotted = cg._dotted(node.func)
                    if dotted is None:
                        continue
                    resolved = cg.resolve_alias(dotted, module)
                    if resolved.split('.')[0] in _DB_MODULES:
                        findings.append(project.finding(
                            self, module, node,
                            f'{resolved}(...) outside the DB access '
                            f'layer — all database connections go '
                            f'through utils/db_utils.py so both '
                            f'backends (sqlite, Postgres) see one '
                            f'source of truth'))
        return findings
