"""db-discipline: ONE database access layer.

ROADMAP item 3 swaps Postgres under the state stores by changing a
single funnel (`utils/db_utils.py` and the four state modules it
serves).  That swap is only a small diff while every sqlite connection
in the tree flows through the funnel — a stray ``sqlite3.connect``
anywhere else becomes a silent second source of truth that the
Postgres backend will not see.  This rule pins the funnel: direct
``sqlite3.connect`` (or holding the ``sqlite3`` import at all) is only
legal in the allowlisted state modules.
"""
from __future__ import annotations

import ast
from typing import List

from skypilot_tpu.analysis import callgraph as cg
from skypilot_tpu.analysis.core import Finding, Project, Rule

# The funnel Postgres will swap under (ROADMAP item 3).
ALLOWED_FILES = (
    'utils/db_utils.py',          # the connection funnel itself
    'global_user_state.py',       # cluster/user state
    'jobs/state.py',              # managed-jobs state
    'serve/serve_state.py',       # serve services/replicas
    'server/requests_db.py',      # API request records
)


class DbDisciplineRule(Rule):
    name = 'db-discipline'
    suppress_token = 'db'
    description = ('direct sqlite3 use outside the state-store funnel '
                   '(utils/db_utils.py + the four state modules)')

    def check(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for module in project.modules:
            if any(module.path.endswith(a) or module.rel.endswith(a)
                   for a in ALLOWED_FILES):
                continue
            for node in ast.walk(module.tree):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        if a.name.split('.')[0] == 'sqlite3':
                            findings.append(project.finding(
                                self, module, node,
                                'import sqlite3 outside the DB access '
                                'layer — all connections must flow '
                                'through utils/db_utils.py (the funnel '
                                'the Postgres backend swaps under)'))
                elif isinstance(node, ast.ImportFrom):
                    if (node.module or '').split('.')[0] == 'sqlite3':
                        findings.append(project.finding(
                            self, module, node,
                            'from sqlite3 import ... outside the DB '
                            'access layer — use utils/db_utils.py'))
                elif isinstance(node, ast.Call):
                    dotted = cg._dotted(node.func)
                    if dotted is None:
                        continue
                    resolved = cg.resolve_alias(dotted, module)
                    if resolved.startswith('sqlite3.'):
                        findings.append(project.finding(
                            self, module, node,
                            f'{resolved}(...) outside the DB access '
                            f'layer — all sqlite goes through '
                            f'utils/db_utils.py so ROADMAP item 3 can '
                            f'swap Postgres under one funnel'))
        return findings
