"""speculation: the verify dispatch stays a fixed, pinned program.

Speculative decoding lives or dies on its dispatch discipline: the
propose/verify loop runs every engine step, so the verify program must
be built ONCE (per bucket/draft-length shape) and pinned like every
other hot-path program.  Two hazards, both of which silently turn the
speculation win into a per-step compile stall:

1. ``jax.jit(...)`` called INSIDE a propose/verify/draft function —
   a fresh wrapper per step defeats the compile cache (each wrapper
   has its own identity), exactly the recompile-hazard loop failure
   mode but reached through the speculation path (these functions are
   called from the engine loop even when they are not lexically inside
   a loop, so the loop-based rule cannot see it).

2. A verify program jitted WITHOUT pinned shardings or donated state
   (``in_shardings``/``out_shardings``/``donate_argnums``/
   ``donate_argnames``): the verify call carries the page pool —
   engine state that must be donated (call k+1 reuses call k's
   buffer) and whose placement must be committed, or input drift
   recompiles mid-traffic and the pool double-buffers in HBM.

The engine's real wiring (``self._verify = jax.jit(self._verify_raw,
donate_argnums=...)`` built once in ``_build_paged_jits``) is clean
under both checks.  Suppress with ``# skytpu: allow-spec(<why>)``.
"""
from __future__ import annotations

import ast
import re
from typing import List

from skypilot_tpu.analysis import callgraph as cg
from skypilot_tpu.analysis.core import (Finding, Project, Rule,
                                        iter_non_def_descendants)

# Function names that constitute the speculation hot loop.
_SPEC_FN_RE = re.compile(r'(propose|verify|draft)', re.IGNORECASE)
_PIN_KWARGS = ('in_shardings', 'out_shardings', 'donate_argnums',
               'donate_argnames')


class SpeculationRule(Rule):
    name = 'speculation'
    suppress_token = 'spec'
    description = ('the speculative verify dispatch must stay jit-'
                   'pinned: no jax.jit inside propose/verify/draft '
                   'functions (fresh wrapper per step = per-step '
                   'compile), and a jitted verify program must pin '
                   'shardings or donate state')

    def check(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for module in project.modules:
            for node in ast.walk(module.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) and \
                        _SPEC_FN_RE.search(node.name):
                    for call in iter_non_def_descendants(node):
                        if isinstance(call, ast.Call) and \
                                cg.is_jit_call(call, module):
                            findings.append(project.finding(
                                self, module, call,
                                f'jax.jit inside {node.name!r}: the '
                                f'propose/verify loop runs every '
                                f'engine step — a fresh jit wrapper '
                                f'per call defeats the compile cache; '
                                f'build the verify program once and '
                                f'dispatch it'))
                if isinstance(node, ast.Call) and \
                        cg.is_jit_call(node, module) and \
                        self._jits_verify_program(node) and \
                        not any(kw.arg in _PIN_KWARGS
                                for kw in node.keywords):
                    findings.append(project.finding(
                        self, module, node,
                        'verify program jitted without pinned '
                        'in/out shardings or donated state — the '
                        'verify call carries the page pool: input '
                        'placement drift recompiles mid-traffic and '
                        'an undonated pool double-buffers in HBM'))
        return findings

    @staticmethod
    def _jits_verify_program(call: ast.Call) -> bool:
        """True when the jitted callee's (dotted) name names a verify
        program (``jax.jit(verify_step)``, ``jax.jit(self._verify_raw,
        ...)``)."""
        if not call.args:
            return False
        dotted = cg._dotted(call.args[0])
        return dotted is not None and \
            'verify' in dotted.split('.')[-1].lower()
