"""Intra-package call graph: which functions are reachable from which.

Rules like hot-loop-sync need "is this `np.asarray` reachable from the
decode loop?", not "is it in engine.py?" — a sync two helper calls away
from the loop costs the same pipelining as one inside it.  The graph is
a deliberately conservative approximation built from names alone (no
type inference, nothing imported):

- module-level functions and class methods are indexed by qualified
  name (``pkg.mod.Class.method``); nested defs get the CPython-style
  ``outer.<locals>.inner`` qualname;
- ``f(...)`` resolves to a same-module def or an imported intra-package
  function; ``mod.f(...)`` through import aliases; ``self.m(...)`` to
  the enclosing class (falling back to same-named methods on sibling
  classes in the module); ``obj.m(...)`` to same-module methods named
  ``m`` (same-file over-approximation, never cross-module guessing);
- calling a class adds an edge to its ``__init__``.

Functions that are jit-wrapped — ``@jax.jit``/``@partial(jax.jit,...)``
decorated, or referenced in a ``jax.jit(fn)`` call — are marked
``jit_wrapped``: their bodies trace once into a compiled program, so
host-sync rules treat them as a different regime (a `np.asarray` there
is a trace-time constant, not a per-step sync).

Hot entry points are declared by a ``# skytpu: hot-entry`` marker on
the def line (self-documenting at the definition), with the known
engine/trainer/RL loops as hardcoded backstops in the sync rule.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from skypilot_tpu.analysis.core import Module

_JIT_NAMES = ('jit',)


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return '.'.join(reversed(parts))
    return None


def is_jit_call(node: ast.Call, module: Module) -> bool:
    """True for jax.jit(...) / pjit(...) / functools.partial(jax.jit,..)
    style calls (resolved through import aliases)."""
    target = _dotted(node.func)
    if target is None:
        return False
    resolved = resolve_alias(target, module)
    if resolved.split('.')[-1] in _JIT_NAMES and \
            resolved.split('.')[0] in ('jax', 'jit'):
        return True
    # functools.partial(jax.jit, ...) — the jit lives in the args.
    if resolved.split('.')[-1] == 'partial' and node.args:
        inner = _dotted(node.args[0])
        if inner is not None:
            r = resolve_alias(inner, module)
            return r.split('.')[-1] in _JIT_NAMES and \
                r.split('.')[0] == 'jax'
    return False


def resolve_alias(dotted: str, module: Module) -> str:
    """Expand the leading segment through the module's import aliases:
    'np.asarray' -> 'numpy.asarray', 'metrics_lib.inc_counter' ->
    'skypilot_tpu.server.metrics.inc_counter'."""
    head, _, rest = dotted.partition('.')
    base = module.import_aliases.get(head)
    if base is None:
        return dotted
    return f'{base}.{rest}' if rest else base


class FunctionInfo:
    __slots__ = ('qualname', 'module', 'node', 'is_async', 'class_name',
                 'jit_wrapped', 'calls')

    def __init__(self, qualname: str, module: Module, node,
                 class_name: Optional[str]) -> None:
        self.qualname = qualname
        self.module = module
        self.node = node
        self.is_async = isinstance(node, ast.AsyncFunctionDef)
        self.class_name = class_name
        self.jit_wrapped = False
        self.calls: List[ast.Call] = []


class _Indexer(ast.NodeVisitor):
    def __init__(self, module: Module, graph: 'CallGraph') -> None:
        self.module = module
        self.graph = graph
        self.class_stack: List[str] = []
        self.fn_stack: List[FunctionInfo] = []

    def _qual_prefix(self) -> str:
        if self.fn_stack:
            return f'{self.fn_stack[-1].qualname}.<locals>'
        if self.class_stack:
            return (f'{self.module.modname}.'
                    f'{".".join(self.class_stack)}')
        return self.module.modname

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    def _visit_fn(self, node) -> None:
        qual = f'{self._qual_prefix()}.{node.name}'
        info = FunctionInfo(
            qual, self.module, node,
            self.class_stack[-1] if (self.class_stack and
                                     not self.fn_stack) else None)
        for dec in node.decorator_list:
            if isinstance(dec, ast.Call) and \
                    is_jit_call(dec, self.module):
                info.jit_wrapped = True
            else:
                target = _dotted(dec)
                if target is not None and resolve_alias(
                        target, self.module).split('.')[-1] in _JIT_NAMES:
                    info.jit_wrapped = True
        self.graph.add_function(info)
        self.fn_stack.append(info)
        self.generic_visit(node)
        self.fn_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_Call(self, node: ast.Call) -> None:
        if self.fn_stack:
            self.fn_stack[-1].calls.append(node)
        # jax.jit(fn): mark a by-name-referenced local def jit-wrapped.
        if is_jit_call(node, self.module):
            for arg in node.args[:1]:
                name = _dotted(arg)
                if name is not None:
                    self.graph.mark_jit(self.module, name.split('.')[-1])
        self.generic_visit(node)


class CallGraph:
    def __init__(self, modules: List[Module]) -> None:
        self.functions: Dict[str, FunctionInfo] = {}
        # module -> {bare fn name -> [qualnames]} for local resolution.
        self._by_module: Dict[str, Dict[str, List[str]]] = {}
        self._pending_jit: List = []
        self._modules = {m.modname: m for m in modules}
        for m in modules:
            _Indexer(m, self).visit(m.tree)
        self._edges: Dict[str, Set[str]] = {}
        for info in self.functions.values():
            self._edges[info.qualname] = self._resolve_calls(info)

    # ----- construction ------------------------------------------------------
    def add_function(self, info: FunctionInfo) -> None:
        self.functions[info.qualname] = info
        names = self._by_module.setdefault(info.module.modname, {})
        names.setdefault(info.node.name, []).append(info.qualname)

    def mark_jit(self, module: Module, bare_name: str) -> None:
        # Defs can be indexed after the jit call is seen (same pass):
        # apply lazily against the final index.
        self._pending_jit.append((module.modname, bare_name))

    def _apply_pending_jit(self) -> None:
        for modname, bare in self._pending_jit:
            for qual in self._by_module.get(modname, {}).get(bare, []):
                self.functions[qual].jit_wrapped = True
        self._pending_jit = []

    def _resolve_calls(self, info: FunctionInfo) -> Set[str]:
        self._apply_pending_jit()
        module = info.module
        targets: Set[str] = set()
        local = self._by_module.get(module.modname, {})
        for call in info.calls:
            func = call.func
            if isinstance(func, ast.Name):
                name = func.id
                # Same-module def (module-level or any class's method
                # brought into scope is NOT a thing for bare names —
                # prefer module-level defs).
                for qual in local.get(name, []):
                    fn = self.functions[qual]
                    if fn.class_name is None:
                        targets.add(qual)
                        targets.update(self._init_of(qual))
                resolved = resolve_alias(name, module)
                if resolved != name and resolved in self.functions:
                    targets.add(resolved)
                    targets.update(self._init_of(resolved))
                elif resolved != name:
                    targets.update(self._init_of(resolved))
            elif isinstance(func, ast.Attribute):
                attr = func.attr
                base = _dotted(func.value)
                resolved_base = (resolve_alias(base, module)
                                 if base else None)
                if base in ('self', 'cls') and info.class_name:
                    qual = (f'{module.modname}.{info.class_name}.'
                            f'{attr}')
                    if qual in self.functions:
                        targets.add(qual)
                        continue
                if resolved_base is not None:
                    # Module-alias call: pkg.mod.attr / alias.attr.
                    cand = f'{resolved_base}.{attr}'
                    if cand in self.functions:
                        targets.add(cand)
                        continue
                    init = self._init_of(cand)
                    if init:
                        targets.update(init)
                        continue
                # Fallback: any same-module method with this name
                # (same-file over-approximation only).
                for qual in local.get(attr, []):
                    if self.functions[qual].class_name is not None:
                        targets.add(qual)
        return targets

    def _init_of(self, qualname: str) -> Set[str]:
        """qualname names a class -> its __init__ (constructor call)."""
        init = f'{qualname}.__init__'
        return {init} if init in self.functions else set()

    # ----- queries -----------------------------------------------------------
    def reachable_from(self, entries: Iterable[str]) -> Set[str]:
        """Transitive closure over resolved call edges."""
        seen: Set[str] = set()
        stack = [e for e in entries if e in self.functions]
        while stack:
            cur = stack.pop()
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(self._edges.get(cur, ()))
        return seen

    def entry_points(self, marker: str = 'hot-entry',
                     defaults: Iterable[str] = ()) -> List[str]:
        """Functions carrying the ``# skytpu: hot-entry`` def-line
        marker, plus any of `defaults` (qualname suffixes) present."""
        out: Set[str] = set()
        for qual, info in self.functions.items():
            if info.module.marker_near(info.node, marker):
                out.add(qual)
            else:
                for d in defaults:
                    if qual == d or qual.endswith('.' + d):
                        out.add(qual)
        return sorted(out)
