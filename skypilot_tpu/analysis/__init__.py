"""Hot-path invariant analyzer (`skytpu check`).

AST-based static analysis enforcing the performance and architecture
invariants the benchmarks rest on: one sync per decode step, zero
mid-traffic recompiles, never-blocked event loops, one DB access
layer, bounded outbound IO, metric-registry discipline.  See core.py
for the framework, rules/ for the rule set, and
tests/test_static_analysis.py for the tier-1 zero-findings gate.
"""
from skypilot_tpu.analysis.core import (Finding, Project, Report, Rule,
                                        load_project, run_check)
from skypilot_tpu.analysis.reporters import render_json, render_text

__all__ = ['Finding', 'Project', 'Report', 'Rule', 'load_project',
           'run_check', 'render_json', 'render_text']
