"""Opt-in usage telemetry (parity: sky/usage/usage_lib.py:78
UsageMessageToReport + :295 heartbeat — the reference POSTs to Loki).

Privacy-first redesign: telemetry is OFF unless configured, and the
default sink is a LOCAL JSONL file — operators aggregate it themselves
(ship it with logs/, scrape it, or point `endpoint` at a Loki-style
collector).  Nothing ever leaves the machine without explicit config:

    usage:
      enabled: true
      path: ~/.skytpu/usage.jsonl      # local sink (default)
      endpoint: http://loki:3100/...   # optional HTTP sink
      labels: {team: ml-infra}         # attached to every event

Events are one JSON object per line: schema_version, ts, event
(e.g. 'launch', 'serve_up', 'heartbeat'), user, plus caller fields.
Failures never propagate — telemetry must not break the operation it
observes.  The server's daemon roster emits a periodic heartbeat with
coarse fleet counts (clusters/jobs/services) when enabled.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

SCHEMA_VERSION = 1


def _config() -> Optional[Dict[str, Any]]:
    from skypilot_tpu import sky_config
    cfg = sky_config.get_nested(('usage',), None)
    if not isinstance(cfg, dict) or not cfg.get('enabled'):
        return None
    return cfg


def enabled() -> bool:
    return _config() is not None


def record(event: str, **fields: Any) -> bool:
    """Record one usage event; returns True if it was written.  Never
    raises (telemetry must not break the operation it observes)."""
    try:
        cfg = _config()
        if cfg is None:
            return False
        from skypilot_tpu import users as users_lib
        msg = {
            'schema_version': SCHEMA_VERSION,
            'ts': time.time(),
            'event': event,
            'user': users_lib.current_user().name,
        }
        labels = cfg.get('labels')
        if isinstance(labels, dict):
            msg['labels'] = labels
        msg.update(fields)
        line = json.dumps(msg, default=str)
        path = os.path.expanduser(cfg.get('path') or
                                  '~/.skytpu/usage.jsonl')
        os.makedirs(os.path.dirname(path) or '.', exist_ok=True)
        with open(path, 'a', encoding='utf-8') as f:
            f.write(line + '\n')
        endpoint = cfg.get('endpoint')
        if endpoint:
            # Fire-and-forget: the HTTP sink must never slow down or
            # fail the operation it observes (the local JSONL line is
            # already durable; success below reflects the local sink).
            import threading

            def _post():
                try:
                    import requests as requests_lib
                    requests_lib.post(
                        endpoint, data=line,
                        headers={'Content-Type': 'application/json'},
                        timeout=5)
                except Exception as e:  # pylint: disable=broad-except
                    logger.debug(f'usage endpoint post failed: {e}')

            threading.Thread(target=_post, name='usage-post',
                             daemon=True).start()
        return True
    except Exception as e:  # pylint: disable=broad-except
        logger.debug(f'usage event {event!r} not recorded: {e}')
        return False


def heartbeat() -> bool:
    """Periodic fleet-shape heartbeat (server daemon tick; parity:
    UsageHeartbeatReportEvent, sky/skylet/events.py:153)."""
    if not enabled():
        return False
    try:
        from skypilot_tpu import global_user_state
        from skypilot_tpu.global_user_state import ClusterStatus
        from skypilot_tpu.jobs import state as jobs_state
        from skypilot_tpu.serve import serve_state
        clusters = global_user_state.get_clusters()
        return record(
            'heartbeat',
            clusters=len(clusters),
            clusters_up=sum(1 for c in clusters
                            if c.get('status') is ClusterStatus.UP),
            managed_jobs=len(jobs_state.nonterminal_jobs()),
            services=len(serve_state.list_services()),
        )
    except Exception as e:  # pylint: disable=broad-except
        logger.debug(f'usage heartbeat failed: {e}')
        return False
