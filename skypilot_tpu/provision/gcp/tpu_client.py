"""REST client for the Cloud TPU API (tpu.googleapis.com, v2).

Parity: the reference's GCPTPUVMInstance provisioner
(sky/provision/gcp/instance_utils.py:1205-1699) which drives the same API
via discovery docs.  This client speaks plain REST with `requests` so it can
be pointed at a fake server in tests (`SKYTPU_TPU_API_ENDPOINT`), covering:

- direct node create/get/list/delete (atomic multi-host slice creation);
- queued resources (create/get/delete) — the stockout-friendly path for
  large slices: the request parks in the TPU scheduler queue and turns
  ACTIVE when capacity frees, vs failing fast (wait-vs-failover tradeoff
  handled by the failover engine);
- operation polling with exponential backoff;
- error classification into the framework's typed provision errors
  (stockout vs quota vs bad request), feeding the failover blocklists.
"""
from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

import requests

from skypilot_tpu import exceptions
from skypilot_tpu.utils import common_utils

_DEFAULT_ENDPOINT = 'https://tpu.googleapis.com/v2'

_STOCKOUT_MARKERS = (
    'RESOURCE_EXHAUSTED', 'ZONE_RESOURCE_POOL_EXHAUSTED', 'out of capacity',
    'Insufficient', 'stockout', 'no more capacity',
)
_QUOTA_MARKERS = ('QUOTA', 'quota exceeded', 'Quota')


def classify_http_error(status_code: int, message: str) -> Exception:
    """HTTP error → typed provision error (reference analog:
    FailoverCloudErrorHandlerV2._gcp_handler,
    cloud_vm_ray_backend.py:494)."""
    if any(m.lower() in message.lower() for m in _QUOTA_MARKERS):
        return exceptions.QuotaExceededError(message)
    if status_code == 429 or any(m.lower() in message.lower()
                                 for m in _STOCKOUT_MARKERS):
        return exceptions.InsufficientCapacityError(message)
    return exceptions.ProvisionError(f'TPU API error {status_code}: '
                                     f'{message}')


class TpuClient:
    def __init__(self, project: str,
                 endpoint: Optional[str] = None,
                 session: Optional[requests.Session] = None) -> None:
        self.project = project
        self.endpoint = (endpoint or
                         os.environ.get('SKYTPU_TPU_API_ENDPOINT',
                                        _DEFAULT_ENDPOINT)).rstrip('/')
        self._session = session or requests.Session()

    # ----- auth --------------------------------------------------------------
    def _headers(self) -> Dict[str, str]:
        if self.endpoint != _DEFAULT_ENDPOINT:
            return {}  # fake server in tests: no auth
        # Process-wide shared credential cache (adaptors/gcp.py): one
        # refresh serves every GCP client in this server.
        from skypilot_tpu.adaptors import gcp as gcp_adaptor
        return gcp_adaptor.auth_headers()

    # ----- plumbing ----------------------------------------------------------
    def _request(self, method: str, path: str,
                 body: Optional[Dict[str, Any]] = None,
                 params: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
        url = f'{self.endpoint}/{path.lstrip("/")}'
        resp = self._session.request(method, url, json=body, params=params,
                                     headers=self._headers(), timeout=60)
        if resp.status_code >= 400:
            try:
                message = resp.json().get('error', {}).get('message',
                                                           resp.text)
            except Exception:  # pylint: disable=broad-except
                message = resp.text
            raise classify_http_error(resp.status_code, message)
        return resp.json() if resp.text else {}

    def _zone_path(self, zone: str) -> str:
        return f'projects/{self.project}/locations/{zone}'

    def wait_operation(self, op: Dict[str, Any],
                       timeout_s: float = 900.0) -> Dict[str, Any]:
        """Poll an LRO until done (reference: _wait_for_operation,
        instance_utils.py:1226)."""
        name = op.get('name')
        if name is None or op.get('done'):
            return op
        backoff = common_utils.Backoff(initial=1.0, cap=15.0)
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            cur = self._request('GET', name)
            if cur.get('done'):
                err = cur.get('error')
                if err:
                    raise classify_http_error(int(err.get('code', 500)),
                                              err.get('message', str(err)))
                return cur
            time.sleep(backoff.current_backoff())
        raise exceptions.QueuedResourceTimeoutError(
            f'operation {name} did not finish in {timeout_s}s')

    # ----- nodes (direct create: small slices / on-demand) -------------------
    def create_node(self, zone: str, node_id: str,
                    accelerator_type: str, runtime_version: str,
                    spot: bool = False,
                    labels: Optional[Dict[str, str]] = None,
                    metadata: Optional[Dict[str, str]] = None,
                    network: Optional[str] = None) -> Dict[str, Any]:
        body: Dict[str, Any] = {
            'acceleratorType': accelerator_type,
            'runtimeVersion': runtime_version,
            'labels': labels or {},
            'metadata': metadata or {},
        }
        if spot:
            body['schedulingConfig'] = {'preemptible': True, 'spot': True}
        if network:
            body['networkConfig'] = {'network': network,
                                     'enableExternalIps': True}
        op = self._request('POST', f'{self._zone_path(zone)}/nodes',
                           body=body, params={'nodeId': node_id})
        return self.wait_operation(op)

    def get_node(self, zone: str, node_id: str) -> Dict[str, Any]:
        return self._request('GET',
                             f'{self._zone_path(zone)}/nodes/{node_id}')

    def list_nodes(self, zone: str) -> List[Dict[str, Any]]:
        out = self._request('GET', f'{self._zone_path(zone)}/nodes')
        return out.get('nodes', [])

    def delete_node(self, zone: str, node_id: str) -> None:
        try:
            op = self._request(
                'DELETE', f'{self._zone_path(zone)}/nodes/{node_id}')
        except exceptions.ProvisionError as e:
            if '404' in str(e) or 'not found' in str(e).lower():
                return
            raise
        self.wait_operation(op)

    def stop_node(self, zone: str, node_id: str) -> None:
        op = self._request('POST',
                           f'{self._zone_path(zone)}/nodes/{node_id}:stop')
        self.wait_operation(op)

    def start_node(self, zone: str, node_id: str) -> None:
        op = self._request('POST',
                           f'{self._zone_path(zone)}/nodes/{node_id}:start')
        self.wait_operation(op)

    # ----- queued resources (large slices / spot) ----------------------------
    def create_queued_resource(self, zone: str, qr_id: str, node_id: str,
                               accelerator_type: str, runtime_version: str,
                               spot: bool = False,
                               valid_until_s: Optional[float] = None,
                               labels: Optional[Dict[str, str]] = None,
                               metadata: Optional[Dict[str, str]] = None
                               ) -> Dict[str, Any]:
        node: Dict[str, Any] = {
            'acceleratorType': accelerator_type,
            'runtimeVersion': runtime_version,
            'labels': labels or {},
            'metadata': metadata or {},
        }
        body: Dict[str, Any] = {
            'tpu': {'nodeSpec': [{
                'parent': self._zone_path(zone),
                'nodeId': node_id,
                'node': node,
            }]},
        }
        if spot:
            body['spot'] = {}
        if valid_until_s is not None:
            body['queueingPolicy'] = {
                'validUntilDuration': f'{int(valid_until_s)}s'
            }
        op = self._request('POST',
                           f'{self._zone_path(zone)}/queuedResources',
                           body=body, params={'queuedResourceId': qr_id})
        return op

    def get_queued_resource(self, zone: str, qr_id: str) -> Dict[str, Any]:
        return self._request(
            'GET', f'{self._zone_path(zone)}/queuedResources/{qr_id}')

    def list_queued_resources(self, zone: str) -> List[Dict[str, Any]]:
        out = self._request('GET',
                            f'{self._zone_path(zone)}/queuedResources')
        return out.get('queuedResources', [])

    def delete_queued_resource(self, zone: str, qr_id: str,
                               force: bool = True) -> None:
        try:
            op = self._request(
                'DELETE',
                f'{self._zone_path(zone)}/queuedResources/{qr_id}',
                params={'force': str(force).lower()})
        except exceptions.ProvisionError as e:
            if '404' in str(e) or 'not found' in str(e).lower():
                return
            raise
        self.wait_operation(op)

    def wait_queued_resource_active(self, zone: str, qr_id: str,
                                    timeout_s: float = 1800.0
                                    ) -> Dict[str, Any]:
        """Wait until ACTIVE; FAILED/SUSPENDED → typed error so the
        failover engine can blocklist and move on."""
        backoff = common_utils.Backoff(initial=2.0, cap=30.0)
        deadline = time.time() + timeout_s
        state = 'UNKNOWN'
        while time.time() < deadline:
            qr = self.get_queued_resource(zone, qr_id)
            state = qr.get('state', {}).get('state', 'UNKNOWN')
            if state == 'ACTIVE':
                return qr
            if state in ('FAILED', 'SUSPENDED'):
                detail = str(qr.get('state', {}))
                raise exceptions.InsufficientCapacityError(
                    f'queued resource {qr_id} {state}: {detail}')
            time.sleep(backoff.current_backoff())
        raise exceptions.QueuedResourceTimeoutError(
            f'queued resource {qr_id} not ACTIVE within {timeout_s}s '
            f'(still {state})')


def default_project() -> str:
    project = os.environ.get('SKYTPU_GCP_PROJECT') or os.environ.get(
        'GOOGLE_CLOUD_PROJECT')
    if project:
        return project
    try:
        import google.auth
        _, project = google.auth.default()
        if project:
            return project
    except Exception:  # pylint: disable=broad-except
        pass
    raise exceptions.NoCloudAccessError(
        'No GCP project configured. Set SKYTPU_GCP_PROJECT or '
        'GOOGLE_CLOUD_PROJECT.')
