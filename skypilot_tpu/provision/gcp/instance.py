"""GCP TPU provisioner implementing the dispatch API.

One logical node == one TPU resource (a whole slice; multi-host slices get
all their host VMs atomically from the TPU API — no per-VM gang scheduling
needed, unlike the reference's GPU path).  Node naming:
``<cluster>-<i>`` for node i; queued-resource ids mirror node ids.

TPU semantics carried from the reference:
- pods (multi-host) cannot stop — only delete (sky/clouds/gcp.py:219-226);
- preempted spot TPUs leave a stale PREEMPTED node that must be deleted
  before re-creating (gcp.py:1095-1101) — run_instances reconciles this;
- queued resources are used for spot and large slices, direct create for
  small on-demand slices (instance_utils.py:1501 retry-on-stockout analog).
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import resources as resources_lib
from skypilot_tpu.provision import common
from skypilot_tpu.provision.gcp import tpu_client as tpu_client_lib

# TPU node states → framework InstanceStatus.
_STATE_MAP = {
    'CREATING': common.InstanceStatus.PENDING,
    'STARTING': common.InstanceStatus.PENDING,
    'RESTARTING': common.InstanceStatus.PENDING,
    'REPAIRING': common.InstanceStatus.PENDING,
    'READY': common.InstanceStatus.RUNNING,
    'STOPPING': common.InstanceStatus.STOPPED,
    'STOPPED': common.InstanceStatus.STOPPED,
    'PREEMPTED': common.InstanceStatus.PREEMPTED,
    'TERMINATED': common.InstanceStatus.TERMINATED,
    'DELETING': common.InstanceStatus.TERMINATED,
}

_CLUSTER_LABEL = 'skytpu-cluster'


def _client() -> tpu_client_lib.TpuClient:
    return tpu_client_lib.TpuClient(tpu_client_lib.default_project())


def _node_id(cluster_name: str, i: int) -> str:
    return f'{cluster_name}-{i}'


def _cluster_nodes(client: tpu_client_lib.TpuClient, zone: str,
                   cluster_name: str) -> Dict[str, dict]:
    out = {}
    for node in client.list_nodes(zone):
        labels = node.get('labels', {})
        if labels.get(_CLUSTER_LABEL) == cluster_name:
            out[node['name'].rsplit('/', 1)[-1]] = node
    return out


def run_instances(config: common.ProvisionConfig) -> common.ProvisionRecord:
    if config.zone is None:
        raise exceptions.ProvisionError(
            'GCP TPU provisioning requires a concrete zone '
            '(the optimizer/failover engine supplies one).')
    res = resources_lib.Resources.from_yaml_config(
        dict(config.resources_config))
    tpu = res.tpu
    if tpu is None:
        raise exceptions.ProvisionError(
            'GCP provisioner currently provisions TPU slices only; '
            'CPU controllers run on the local cloud or kubernetes.')
    client = _client()
    zone = config.zone
    existing = _cluster_nodes(client, zone, config.cluster_name)
    labels = dict(config.labels)
    labels[_CLUSTER_LABEL] = config.cluster_name
    metadata = {}
    if config.authorized_key:
        # TPU VMs honor ssh-keys metadata like GCE.
        metadata['ssh-keys'] = f'skytpu:{config.authorized_key}'

    instance_ids = []
    resumed = False
    use_qr = res.use_spot or tpu.is_pod   # queued path for spot/pods
    for i in range(config.num_nodes):
        node_id = _node_id(config.cluster_name, i)
        instance_ids.append(node_id)
        node = existing.get(node_id)
        state = node.get('state') if node else None
        if state == 'READY':
            resumed = True
            continue
        if state in ('CREATING', 'STARTING', 'RESTARTING', 'REPAIRING'):
            # In-flight from an interrupted launch: re-creating would 409
            # and blocklist a healthy zone; wait_instances will pick it up.
            resumed = True
            continue
        if state in ('STOPPED', 'STOPPING'):
            client.start_node(zone, node_id)
            resumed = True
            continue
        if state in ('PREEMPTED', 'TERMINATED', 'FAILED'):
            # Stale spot node: must delete before re-create
            # (reference: sky/clouds/gcp.py:1095-1101).
            client.delete_queued_resource(zone, node_id)
            client.delete_node(zone, node_id)
        if use_qr:
            client.delete_queued_resource(zone, node_id)
            client.create_queued_resource(
                zone, qr_id=node_id, node_id=node_id,
                accelerator_type=tpu.gcp_accelerator_type,
                runtime_version=res.tpu_runtime_version,
                spot=res.use_spot, labels=labels, metadata=metadata)
        else:
            client.create_node(
                zone, node_id,
                accelerator_type=tpu.gcp_accelerator_type,
                runtime_version=res.tpu_runtime_version,
                spot=False, labels=labels, metadata=metadata)
    return common.ProvisionRecord('gcp', config.cluster_name,
                                  config.region, zone, instance_ids,
                                  resumed=resumed)


def _cluster_queued_resources(client: tpu_client_lib.TpuClient, zone: str,
                              cluster_name: str) -> List[str]:
    out = []
    for qr in client.list_queued_resources(zone):
        specs = qr.get('tpu', {}).get('nodeSpec', [])
        labels = specs[0].get('node', {}).get('labels', {}) if specs else {}
        if labels.get(_CLUSTER_LABEL) == cluster_name:
            out.append(qr['name'].rsplit('/', 1)[-1])
    return out


def wait_instances(cluster_name: str, region=None, zone=None,
                   timeout_s: float = 1800.0) -> None:
    client = _client()
    # Queued-resource path first: wait until each QR is ACTIVE (the TPU
    # scheduler materializes the node atomically at that point).
    for qr_id in _cluster_queued_resources(client, zone, cluster_name):
        client.wait_queued_resource_active(zone, qr_id,
                                           timeout_s=timeout_s)
    deadline = time.time() + timeout_s
    while True:
        statuses = query_instances(cluster_name, region, zone)
        if not statuses:
            raise exceptions.ProvisionError(
                f'no TPU nodes found for cluster {cluster_name} in {zone}')
        if all(s is common.InstanceStatus.RUNNING
               for s in statuses.values()):
            return
        bad = {k: s for k, s in statuses.items() if s in
               (common.InstanceStatus.PREEMPTED,
                common.InstanceStatus.TERMINATED)}
        if bad:
            raise exceptions.InsufficientCapacityError(
                f'TPU nodes failed during provisioning: {bad}')
        if time.time() > deadline:
            raise exceptions.QueuedResourceTimeoutError(
                f'cluster {cluster_name} not READY in {timeout_s}s: '
                f'{statuses}')
        time.sleep(10.0)
    del client


def query_instances(cluster_name: str, region=None,
                    zone=None) -> Dict[str, common.InstanceStatus]:
    client = _client()
    nodes = _cluster_nodes(client, zone, cluster_name)
    return {
        node_id: _STATE_MAP.get(node.get('state', ''),
                                common.InstanceStatus.PENDING)
        for node_id, node in nodes.items()
    }


def stop_instances(cluster_name: str, region=None, zone=None) -> None:
    client = _client()
    for node_id, node in _cluster_nodes(client, zone, cluster_name).items():
        accel = node.get('acceleratorType', '')
        # Multi-host slice: no stop support in the TPU API.
        from skypilot_tpu import accelerators as acc_lib
        if acc_lib.is_tpu(f'tpu-{accel}') and \
                acc_lib.parse_tpu(f'tpu-{accel}').is_pod:
            raise exceptions.NotSupportedError(
                f'TPU pod slice {node_id} ({accel}) cannot be stopped; '
                'use down instead.')
        client.stop_node(zone, node_id)


def terminate_instances(cluster_name: str, region=None, zone=None) -> None:
    client = _client()
    # Parked queued-resources whose node never materialized need explicit
    # deletion too (otherwise they later claim capacity for a dead cluster).
    for qr_id in _cluster_queued_resources(client, zone, cluster_name):
        client.delete_queued_resource(zone, qr_id)
    for node_id in _cluster_nodes(client, zone, cluster_name):
        client.delete_queued_resource(zone, node_id)
        client.delete_node(zone, node_id)


def get_cluster_info(cluster_name: str, region=None,
                     zone=None) -> common.ClusterInfo:
    client = _client()
    instances: List[common.InstanceInfo] = []
    def _numeric_key(item):
        # '<cluster>-<i>': order by node index, not lexicographically
        # (lexicographic puts node 10 before node 2).
        node_id = item[0]
        suffix = node_id.rsplit('-', 1)[-1]
        return (int(suffix) if suffix.isdigit() else 1 << 30, node_id)

    for node_id, node in sorted(
            _cluster_nodes(client, zone, cluster_name).items(),
            key=_numeric_key):
        internal, external = [], []
        for ep in node.get('networkEndpoints', []):
            if ep.get('ipAddress'):
                internal.append(ep['ipAddress'])
            access = ep.get('accessConfig', {})
            if access.get('externalIp'):
                external.append(access['externalIp'])
        instances.append(
            common.InstanceInfo(
                instance_id=node_id,
                status=_STATE_MAP.get(node.get('state', ''),
                                      common.InstanceStatus.PENDING),
                internal_ips=internal,
                external_ips=external,
                tags=node.get('labels', {}),
            ))
    return common.ClusterInfo('gcp', cluster_name, instances,
                              ssh_user='skytpu')
