"""GCP provisioner implementing the dispatch API: TPU slices + CPU VMs.

One logical node == one TPU resource (a whole slice; multi-host slices get
all their host VMs atomically from the TPU API — no per-VM gang scheduling
needed, unlike the reference's GPU path).  Node naming:
``<cluster>-<i>`` for node i; queued-resource ids mirror node ids.

Resources without a TPU route to Compute Engine (gce_client.py — the
reference's GCPComputeInstance, sky/provision/gcp/instance_utils.py:311):
serve LBs/controllers and CPU-only tasks.  The read/teardown paths
(query/stop/terminate/get_cluster_info) consult both services and merge,
since the dispatch API addresses clusters by name only.

TPU semantics carried from the reference:
- pods (multi-host) cannot stop — only delete (sky/clouds/gcp.py:219-226);
- preempted spot TPUs leave a stale PREEMPTED node that must be deleted
  before re-creating (gcp.py:1095-1101) — run_instances reconciles this;
- queued resources are used for spot and large slices, direct create for
  small on-demand slices (instance_utils.py:1501 retry-on-stockout analog).
"""
from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import resources as resources_lib
from skypilot_tpu.provision import common
from skypilot_tpu.provision.gcp import gce_client as gce_client_lib
from skypilot_tpu.provision.gcp import tpu_client as tpu_client_lib

# TPU node states → framework InstanceStatus.
_STATE_MAP = {
    'CREATING': common.InstanceStatus.PENDING,
    'STARTING': common.InstanceStatus.PENDING,
    'RESTARTING': common.InstanceStatus.PENDING,
    'REPAIRING': common.InstanceStatus.PENDING,
    'READY': common.InstanceStatus.RUNNING,
    'STOPPING': common.InstanceStatus.STOPPED,
    'STOPPED': common.InstanceStatus.STOPPED,
    'PREEMPTED': common.InstanceStatus.PREEMPTED,
    'TERMINATED': common.InstanceStatus.TERMINATED,
    'DELETING': common.InstanceStatus.TERMINATED,
}

# GCE instance states → framework InstanceStatus.  Note GCE reports a
# *stopped* VM as TERMINATED (the disk survives; the instance restarts).
_GCE_STATE_MAP = {
    'PROVISIONING': common.InstanceStatus.PENDING,
    'STAGING': common.InstanceStatus.PENDING,
    'REPAIRING': common.InstanceStatus.PENDING,
    'RUNNING': common.InstanceStatus.RUNNING,
    'STOPPING': common.InstanceStatus.STOPPED,
    'SUSPENDING': common.InstanceStatus.STOPPED,
    'SUSPENDED': common.InstanceStatus.STOPPED,
    'TERMINATED': common.InstanceStatus.STOPPED,
}

_CLUSTER_LABEL = 'skytpu-cluster'

# instances.start/resume (and delete-then-recreate of stale spot nodes)
# are async on the real APIs: for a while after we issue the call the
# instance still reports its old TERMINATED/SUSPENDED/STOPPED state.
# run_instances stamps such nodes here so wait_instances treats those
# states as in-flight (PENDING) instead of spuriously classifying the
# cluster as failed — which would send the failover engine off to delete
# a perfectly healthy restarting VM.
_RESUME_GRACE_S = 120.0
_recent_restarts: Dict[str, float] = {}


def _mark_restarting(node_id: str) -> None:
    now = time.time()
    for k in [k for k, t in _recent_restarts.items()
              if now - t >= _RESUME_GRACE_S]:
        del _recent_restarts[k]
    _recent_restarts[node_id] = now


def _in_restart_grace(node_id: str) -> bool:
    t = _recent_restarts.get(node_id)
    return t is not None and time.time() - t < _RESUME_GRACE_S


def _client() -> tpu_client_lib.TpuClient:
    return tpu_client_lib.TpuClient(tpu_client_lib.default_project())


def _gce_client() -> gce_client_lib.GceClient:
    return gce_client_lib.GceClient(tpu_client_lib.default_project())


def _node_id(cluster_name: str, i: int) -> str:
    return f'{cluster_name}-{i}'


def _cluster_nodes(client: tpu_client_lib.TpuClient, zone: str,
                   cluster_name: str) -> Dict[str, dict]:
    out = {}
    for node in client.list_nodes(zone):
        labels = node.get('labels', {})
        if labels.get(_CLUSTER_LABEL) == cluster_name:
            out[node['name'].rsplit('/', 1)[-1]] = node
    return out


def run_instances(config: common.ProvisionConfig) -> common.ProvisionRecord:
    if config.zone is None:
        raise exceptions.ProvisionError(
            'GCP TPU provisioning requires a concrete zone '
            '(the optimizer/failover engine supplies one).')
    res = resources_lib.Resources.from_yaml_config(
        dict(config.resources_config))
    tpu = res.tpu
    if tpu is None:
        return _run_gce_instances(config, res)
    if config.volumes:
        # Loud, not silent: TPU slices have no disk-attach path; data
        # that must survive the slice belongs on bucket mounts.
        raise exceptions.InvalidRequestError(
            'gcp-disk volumes cannot attach to TPU slices; use storage '
            '(bucket) mounts for checkpoints/datasets on TPUs')
    from skypilot_tpu.provision import docker_utils
    if res.image_id and not docker_utils.image_from_resources(
            res.image_id):
        raise exceptions.InvalidRequestError(
            'image_id does not apply to TPU slices; their software '
            'stack is selected by the TPU runtime version (the '
            '`runtime_version` resources field).  `docker:<image>` IS '
            'supported — the task runs in a privileged container on '
            'each TPU VM host')
    client = _client()
    zone = config.zone
    existing = _cluster_nodes(client, zone, config.cluster_name)
    labels = dict(config.labels)
    labels[_CLUSTER_LABEL] = config.cluster_name
    metadata = {}
    if config.authorized_key:
        # TPU VMs honor ssh-keys metadata like GCE.
        metadata['ssh-keys'] = f'skytpu:{config.authorized_key}'

    instance_ids = []
    resumed = False
    use_qr = res.use_spot or tpu.is_pod   # queued path for spot/pods
    for i in range(config.num_nodes):
        node_id = _node_id(config.cluster_name, i)
        instance_ids.append(node_id)
        node = existing.get(node_id)
        state = node.get('state') if node else None
        if state == 'READY':
            resumed = True
            continue
        if state in ('CREATING', 'STARTING', 'RESTARTING', 'REPAIRING'):
            # In-flight from an interrupted launch: re-creating would 409
            # and blocklist a healthy zone; wait_instances will pick it up.
            resumed = True
            continue
        if state in ('STOPPED', 'STOPPING'):
            client.start_node(zone, node_id)
            _mark_restarting(node_id)
            resumed = True
            continue
        if state in ('PREEMPTED', 'TERMINATED', 'FAILED'):
            # Stale spot node: must delete before re-create
            # (reference: sky/clouds/gcp.py:1095-1101).
            client.delete_queued_resource(zone, node_id)
            client.delete_node(zone, node_id)
            _mark_restarting(node_id)
        if use_qr:
            client.delete_queued_resource(zone, node_id)
            client.create_queued_resource(
                zone, qr_id=node_id, node_id=node_id,
                accelerator_type=tpu.gcp_accelerator_type,
                runtime_version=res.tpu_runtime_version,
                spot=res.use_spot, labels=labels, metadata=metadata)
        else:
            client.create_node(
                zone, node_id,
                accelerator_type=tpu.gcp_accelerator_type,
                runtime_version=res.tpu_runtime_version,
                spot=False, labels=labels, metadata=metadata)
    return common.ProvisionRecord('gcp', config.cluster_name,
                                  config.region, zone, instance_ids,
                                  resumed=resumed)


def _gce_cluster_instances(client: gce_client_lib.GceClient, zone: str,
                           cluster_name: str) -> Dict[str, dict]:
    out = {}
    for inst in client.list_instances(zone):
        if inst.get('labels', {}).get(_CLUSTER_LABEL) == cluster_name:
            out[inst['name']] = inst
    return out


def _run_gce_instances(config: common.ProvisionConfig,
                       res: resources_lib.Resources
                       ) -> common.ProvisionRecord:
    """CPU-VM path (reference: GCPComputeInstance.create_instances,
    instance_utils.py:311-788)."""
    machine_type = res.instance_type
    if machine_type is None:
        from skypilot_tpu.catalog import gcp_catalog
        machine_type = gcp_catalog.get_default_instance_type(
            res.cpus, res.memory)
    if machine_type is None:
        raise exceptions.ProvisionError(
            f'no GCE machine type satisfies cpus={res.cpus} '
            f'memory={res.memory}')
    client = _gce_client()
    zone = config.zone
    existing = _gce_cluster_instances(client, zone, config.cluster_name)
    labels = dict(config.labels)
    labels[_CLUSTER_LABEL] = config.cluster_name
    metadata = {}
    if config.authorized_key:
        metadata['ssh-keys'] = f'skytpu:{config.authorized_key}'
    attach_disks = sorted(config.volumes.values()) or None
    # docker:<image> is a task RUNTIME (container on the VM), not a VM
    # boot image — the gang executor handles it (agent/gang.py).
    from skypilot_tpu.provision import docker_utils
    source_image = (None if docker_utils.image_from_resources(
        res.image_id) else res.image_id)
    disk_size_gb = int(res.disk_size)
    if attach_disks:
        # Format-if-new and mount each named disk at its mount_path on
        # boot (the k8s path gets this from the kubelet; VMs need it
        # spelled out).
        lines = ['#!/bin/bash']
        for mount_path, disk in sorted(config.volumes.items()):
            dev = f'/dev/disk/by-id/google-{disk}'
            lines += [
                f'if ! blkid {dev} >/dev/null 2>&1; then '
                f'mkfs.ext4 -m 0 -F {dev}; fi',
                f'mkdir -p {mount_path}',
                f'mount -o discard,defaults {dev} {mount_path}',
            ]
        metadata['startup-script'] = '\n'.join(lines)

    def _check_volumes_attached(inst: dict, name: str) -> None:
        """An existing instance must already carry every requested
        volume — new volumes cannot be hot-added to a reused VM."""
        if not attach_disks:
            return
        have = {d.get('deviceName') for d in inst.get('disks', [])}
        missing = [d for d in attach_disks if d not in have]
        if missing:
            raise exceptions.InvalidRequestError(
                f'instance {name} exists without volumes {missing} '
                f'attached; `skytpu down` the cluster and relaunch to '
                f'attach them')

    instance_ids = []
    to_create = []
    resumed = False
    for i in range(config.num_nodes):
        name = _node_id(config.cluster_name, i)
        instance_ids.append(name)
        inst = existing.get(name)
        status = inst.get('status') if inst else None
        if status in ('RUNNING', 'PROVISIONING', 'STAGING'):
            _check_volumes_attached(inst, name)
            resumed = True
            continue
        if status in ('TERMINATED', 'STOPPING'):
            # GCE TERMINATED == stopped-with-disk: restart in place.  An
            # in-flight stop must settle first — start on a STOPPING
            # instance is a 400 on the real API.
            _check_volumes_attached(inst, name)
            if status == 'STOPPING':
                client.wait_instance_status(zone, name, ('TERMINATED',))
            # No grace stamp needed: GCE stale post-start states
            # (TERMINATED/SUSPENDED) map to InstanceStatus.STOPPED, which
            # wait_instances already treats as in-flight.
            client.start_instance(zone, name)
            resumed = True
            continue
        if status in ('SUSPENDED', 'SUSPENDING'):
            _check_volumes_attached(inst, name)
            if status == 'SUSPENDING':
                client.wait_instance_status(zone, name, ('SUSPENDED',))
            client.resume_instance(zone, name)
            resumed = True
            continue
        to_create.append(name)
    if len(to_create) == 1:
        client.create_instance(zone, to_create[0], machine_type,
                               spot=res.use_spot, labels=labels,
                               metadata=metadata,
                               disk_size_gb=disk_size_gb,
                               attach_disks=attach_disks,
                               source_image=source_image)
    elif to_create:
        if attach_disks:
            # A zonal persistent disk attaches to one VM (ReadWriteOnce);
            # multi-node gangs must use bucket mounts instead.
            raise exceptions.InvalidRequestError(
                'gcp-disk volumes attach to single-node clusters only; '
                'use storage (bucket) mounts for multi-node tasks')
        client.bulk_create_instances(zone, to_create, machine_type,
                                     spot=res.use_spot, labels=labels,
                                     metadata=metadata,
                                     disk_size_gb=disk_size_gb,
                                     source_image=source_image)
    return common.ProvisionRecord('gcp', config.cluster_name,
                                  config.region, zone, instance_ids,
                                  resumed=resumed)


def _cluster_queued_resources(client: tpu_client_lib.TpuClient, zone: str,
                              cluster_name: str) -> List[str]:
    out = []
    for qr in client.list_queued_resources(zone):
        specs = qr.get('tpu', {}).get('nodeSpec', [])
        labels = specs[0].get('node', {}).get('labels', {}) if specs else {}
        if labels.get(_CLUSTER_LABEL) == cluster_name:
            out.append(qr['name'].rsplit('/', 1)[-1])
    return out


def _service_unconfigured(e: Exception) -> bool:
    """True iff the error means this deployment simply has no access to
    that service (no project/credentials) — by-design absence.  Anything
    else (500s, timeouts, auth blips) is a REAL error: treating it as
    'no instances' would let teardown silently leak billed resources and
    status refresh remove live clusters."""
    if isinstance(e, exceptions.NoCloudAccessError):
        return True
    # DefaultCredentialsError = no credentials at all (by-design absence).
    # RefreshError is NOT here: credentials exist but refresh failed —
    # a transient auth problem that must surface, not read as empty.
    return type(e).__name__ == 'DefaultCredentialsError'


def _query_both(cluster_name: str, zone: str):
    """(tpu_nodes, gce_instances).  A side whose service is not
    configured for this deployment (CPU-only: no TPU API; TPU-only: no
    GCE) reads as empty; a side that is configured but *fails* raises —
    callers must not mistake an outage for an empty cluster."""
    unconfigured = []
    tpu_nodes: Dict[str, dict] = {}
    gce_insts: Dict[str, dict] = {}
    try:
        tpu_nodes = _cluster_nodes(_client(), zone, cluster_name)
    except Exception as e:  # pylint: disable=broad-except
        if not _service_unconfigured(e):
            raise
        unconfigured.append(e)
    try:
        gce_insts = _gce_cluster_instances(_gce_client(), zone,
                                           cluster_name)
    except Exception as e:  # pylint: disable=broad-except
        if not _service_unconfigured(e):
            raise
        unconfigured.append(e)
    if len(unconfigured) == 2:
        raise unconfigured[0]
    return tpu_nodes, gce_insts


def _queued_resource_wait_s(default: float) -> float:
    """Wait-vs-failover policy knob (SURVEY hard-part (d); reference:
    retry-on-stockout loop, instance_utils.py:1501-1592): how long to park
    on a queued resource before abandoning the zone.  A long wait bets the
    zone frees up; a short one lets the failover engine try elsewhere.
    Config: `gcp.queued_resource_wait_s` (yaml) or
    SKYTPU_QUEUED_RESOURCE_WAIT_S (env, wins)."""
    env = os.environ.get('SKYTPU_QUEUED_RESOURCE_WAIT_S')
    if env is not None:
        return float(env)
    from skypilot_tpu import sky_config
    return float(sky_config.get_nested(('gcp', 'queued_resource_wait_s'),
                                       default))


def wait_instances(cluster_name: str, region=None, zone=None,
                   timeout_s: float = 1800.0) -> None:
    # Queued-resource path first: wait until each QR is ACTIVE (the TPU
    # scheduler materializes the node atomically at that point).  On
    # timeout, QueuedResourceTimeoutError propagates to the failover
    # engine, which blocklists this zone, deletes the parked QR
    # (cleanup_fn) and tries the next placement.
    try:
        client = _client()
        qr_ids = _cluster_queued_resources(client, zone, cluster_name)
    except Exception as e:  # pylint: disable=broad-except
        if not _service_unconfigured(e):
            raise
        client, qr_ids = None, []   # CPU-only deployment: no TPU API
    qr_wait = _queued_resource_wait_s(timeout_s)
    for qr_id in qr_ids:
        client.wait_queued_resource_active(zone, qr_id,
                                           timeout_s=qr_wait)
    deadline = time.time() + timeout_s
    while True:
        statuses = query_instances(cluster_name, region, zone)
        if not statuses:
            raise exceptions.ProvisionError(
                f'no instances found for cluster {cluster_name} in {zone}')
        if all(s is common.InstanceStatus.RUNNING
               for s in statuses.values()):
            return
        bad = {k: s for k, s in statuses.items()
               if s in (common.InstanceStatus.PREEMPTED,
                        common.InstanceStatus.TERMINATED)
               and not _in_restart_grace(k)}
        if bad:
            raise exceptions.InsufficientCapacityError(
                f'instances failed during provisioning: {bad}')
        if time.time() > deadline:
            raise exceptions.QueuedResourceTimeoutError(
                f'cluster {cluster_name} not READY in {timeout_s}s: '
                f'{statuses}')
        time.sleep(float(os.environ.get('SKYTPU_PROVISION_POLL_S', '10')))
    del client


def query_instances(cluster_name: str, region=None,
                    zone=None) -> Dict[str, common.InstanceStatus]:
    tpu_nodes, gce_insts = _query_both(cluster_name, zone)
    out = {
        node_id: _STATE_MAP.get(node.get('state', ''),
                                common.InstanceStatus.PENDING)
        for node_id, node in tpu_nodes.items()
    }
    for name, inst in gce_insts.items():
        out[name] = _GCE_STATE_MAP.get(inst.get('status', ''),
                                       common.InstanceStatus.PENDING)
    return out


def stop_instances(cluster_name: str, region=None, zone=None) -> None:
    tpu_nodes, gce_insts = _query_both(cluster_name, zone)
    if tpu_nodes:
        client = _client()
        for node_id, node in tpu_nodes.items():
            accel = node.get('acceleratorType', '')
            # Multi-host slice: no stop support in the TPU API.
            from skypilot_tpu import accelerators as acc_lib
            if acc_lib.is_tpu(f'tpu-{accel}') and \
                    acc_lib.parse_tpu(f'tpu-{accel}').is_pod:
                raise exceptions.NotSupportedError(
                    f'TPU pod slice {node_id} ({accel}) cannot be '
                    'stopped; use down instead.')
            client.stop_node(zone, node_id)
    if gce_insts:
        gce = _gce_client()
        for name in gce_insts:
            gce.stop_instance(zone, name)


def terminate_instances(cluster_name: str, region=None, zone=None) -> None:
    tpu_nodes, gce_insts = _query_both(cluster_name, zone)
    try:
        client = _client()
        qr_ids = _cluster_queued_resources(client, zone, cluster_name)
    except Exception as e:  # pylint: disable=broad-except
        if not _service_unconfigured(e):
            raise
        client, qr_ids = None, []
    # Parked queued-resources whose node never materialized need explicit
    # deletion too (otherwise they later claim capacity for a dead cluster).
    for qr_id in qr_ids:
        client.delete_queued_resource(zone, qr_id)
    for node_id in tpu_nodes:
        client.delete_queued_resource(zone, node_id)
        client.delete_node(zone, node_id)
    if gce_insts:
        gce = _gce_client()
        for name in gce_insts:
            gce.delete_instance(zone, name)


def get_cluster_info(cluster_name: str, region=None,
                     zone=None) -> common.ClusterInfo:
    tpu_nodes, gce_insts = _query_both(cluster_name, zone)
    instances: List[common.InstanceInfo] = []

    def _numeric_key(item):
        # '<cluster>-<i>': order by node index, not lexicographically
        # (lexicographic puts node 10 before node 2).
        node_id = item[0]
        suffix = node_id.rsplit('-', 1)[-1]
        return (int(suffix) if suffix.isdigit() else 1 << 30, node_id)

    for node_id, node in sorted(tpu_nodes.items(), key=_numeric_key):
        internal, external = [], []
        for ep in node.get('networkEndpoints', []):
            if ep.get('ipAddress'):
                internal.append(ep['ipAddress'])
            access = ep.get('accessConfig', {})
            if access.get('externalIp'):
                external.append(access['externalIp'])
        instances.append(
            common.InstanceInfo(
                instance_id=node_id,
                status=_STATE_MAP.get(node.get('state', ''),
                                      common.InstanceStatus.PENDING),
                internal_ips=internal,
                external_ips=external,
                tags=node.get('labels', {}),
            ))
    for name, inst in sorted(gce_insts.items(), key=_numeric_key):
        internal, external = [], []
        for nic in inst.get('networkInterfaces', []):
            if nic.get('networkIP'):
                internal.append(nic['networkIP'])
            for access in nic.get('accessConfigs', []):
                if access.get('natIP'):
                    external.append(access['natIP'])
        instances.append(
            common.InstanceInfo(
                instance_id=name,
                status=_GCE_STATE_MAP.get(inst.get('status', ''),
                                          common.InstanceStatus.PENDING),
                internal_ips=internal,
                external_ips=external,
                tags=inst.get('labels', {}),
            ))
    return common.ClusterInfo('gcp', cluster_name, instances,
                              ssh_user='skytpu')
