"""REST client for Compute Engine (compute.googleapis.com, v1).

Parity: the reference's GCPComputeInstance provisioner
(sky/provision/gcp/instance_utils.py:311, bulk insert :788) which drives
the same API via discovery docs.  Plain REST with `requests` so tests can
point it at a fake server (`SKYTPU_GCE_API_ENDPOINT`).  CPU VMs carry the
control-plane workloads TPU slices can't: serve load balancers and
controllers, CPU-only tasks.

Shares the TPU client's auth + error-classification (same project, same
google.auth flow, same stockout/quota taxonomy feeding the failover
blocklists).
"""
from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

import requests

from skypilot_tpu import exceptions
from skypilot_tpu.provision.gcp import tpu_client as tpu_client_lib
from skypilot_tpu.utils import common_utils

_DEFAULT_ENDPOINT = 'https://compute.googleapis.com/compute/v1'

_DEFAULT_IMAGE = ('projects/debian-cloud/global/images/family/'
                  'debian-12')


class GceClient:
    def __init__(self, project: str,
                 endpoint: Optional[str] = None,
                 session: Optional[requests.Session] = None) -> None:
        self.project = project
        self.endpoint = (endpoint or
                         os.environ.get('SKYTPU_GCE_API_ENDPOINT',
                                        _DEFAULT_ENDPOINT)).rstrip('/')
        self._session = session or requests.Session()

    # ----- auth --------------------------------------------------------------
    def _headers(self) -> Dict[str, str]:
        if self.endpoint != _DEFAULT_ENDPOINT:
            return {}  # fake server in tests: no auth
        # Process-wide shared credential cache (adaptors/gcp.py).
        from skypilot_tpu.adaptors import gcp as gcp_adaptor
        return gcp_adaptor.auth_headers()

    def _request(self, method: str, path: str,
                 body: Optional[Dict[str, Any]] = None,
                 params: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
        url = f'{self.endpoint}/{path.lstrip("/")}'
        resp = self._session.request(method, url, json=body, params=params,
                                     headers=self._headers(), timeout=60)
        if resp.status_code >= 400:
            try:
                message = resp.json().get('error', {}).get('message',
                                                           resp.text)
            except Exception:  # pylint: disable=broad-except
                message = resp.text
            raise tpu_client_lib.classify_http_error(resp.status_code,
                                                     message)
        return resp.json() if resp.text else {}

    def _zone_path(self, zone: str) -> str:
        return f'projects/{self.project}/zones/{zone}'

    def wait_zone_operation(self, zone: str, op: Dict[str, Any],
                            timeout_s: float = 600.0) -> Dict[str, Any]:
        name = op.get('name')
        if name is None or op.get('status') == 'DONE':
            self._raise_op_error(op)
            return op
        backoff = common_utils.Backoff(initial=1.0, cap=10.0)
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            cur = self._request(
                'GET', f'{self._zone_path(zone)}/operations/{name}')
            if cur.get('status') == 'DONE':
                self._raise_op_error(cur)
                return cur
            time.sleep(backoff.current_backoff())
        raise exceptions.ProvisionError(
            f'GCE operation {name} did not finish in {timeout_s}s')

    @staticmethod
    def _raise_op_error(op: Dict[str, Any]) -> None:
        errors = op.get('error', {}).get('errors', [])
        if errors:
            message = '; '.join(e.get('message', e.get('code', ''))
                                for e in errors)
            raise tpu_client_lib.classify_http_error(
                int(op.get('httpErrorStatusCode', 500)), message)

    # ----- instances ---------------------------------------------------------
    def _instance_body(self, zone: str, name: str, machine_type: str,
                       spot: bool,
                       labels: Optional[Dict[str, str]],
                       metadata: Optional[Dict[str, str]],
                       disk_size_gb: int,
                       attach_disks: Optional[List[str]] = None,
                       source_image: Optional[str] = None
                       ) -> Dict[str, Any]:
        body: Dict[str, Any] = {
            'name': name,
            'machineType': f'zones/{zone}/machineTypes/{machine_type}',
            'disks': [{
                'boot': True,
                'autoDelete': True,
                'initializeParams': {
                    'sourceImage': source_image or _DEFAULT_IMAGE,
                    'diskSizeGb': str(disk_size_gb),
                },
            }] + [{
                # Named persistent-disk volumes (volumes.py): attached
                # non-boot, never auto-deleted — they outlive the VM.
                'boot': False,
                'autoDelete': False,
                'deviceName': disk,
                'source': f'{self._zone_path(zone)}/disks/{disk}',
            } for disk in (attach_disks or [])],
            'networkInterfaces': [{
                'network': 'global/networks/default',
                'accessConfigs': [{'type': 'ONE_TO_ONE_NAT',
                                   'name': 'External NAT'}],
            }],
            'labels': labels or {},
            'metadata': {
                'items': [{'key': k, 'value': v}
                          for k, v in (metadata or {}).items()],
            },
        }
        if spot:
            body['scheduling'] = {
                'provisioningModel': 'SPOT',
                'instanceTerminationAction': 'DELETE',
            }
        return body

    def create_instance(self, zone: str, name: str, machine_type: str,
                        spot: bool = False,
                        labels: Optional[Dict[str, str]] = None,
                        metadata: Optional[Dict[str, str]] = None,
                        disk_size_gb: int = 100,
                        attach_disks: Optional[List[str]] = None,
                        source_image: Optional[str] = None) -> None:
        body = self._instance_body(zone, name, machine_type, spot, labels,
                                   metadata, disk_size_gb, attach_disks,
                                   source_image)
        op = self._request('POST', f'{self._zone_path(zone)}/instances',
                           body=body)
        self.wait_zone_operation(zone, op)

    def bulk_create_instances(self, zone: str, names: List[str],
                              machine_type: str, spot: bool = False,
                              labels: Optional[Dict[str, str]] = None,
                              metadata: Optional[Dict[str, str]] = None,
                              disk_size_gb: int = 100,
                              source_image: Optional[str] = None) -> None:
        """One bulkInsert call for N homogeneous VMs (reference:
        instance_utils.py:788) — atomic-ish gang creation for multi-node
        CPU clusters."""
        props = self._instance_body(zone, '', machine_type, spot, labels,
                                    metadata, disk_size_gb,
                                    source_image=source_image)
        props.pop('name')
        body = {
            'count': str(len(names)),
            'perInstanceProperties': {n: {'name': n} for n in names},
            'instanceProperties': props,
        }
        op = self._request(
            'POST', f'{self._zone_path(zone)}/instances/bulkInsert',
            body=body)
        self.wait_zone_operation(zone, op)

    def get_instance(self, zone: str, name: str) -> Dict[str, Any]:
        return self._request('GET',
                             f'{self._zone_path(zone)}/instances/{name}')

    def list_instances(self, zone: str) -> List[Dict[str, Any]]:
        out = self._request('GET', f'{self._zone_path(zone)}/instances')
        return out.get('items', [])

    def delete_instance(self, zone: str, name: str) -> None:
        try:
            op = self._request(
                'DELETE', f'{self._zone_path(zone)}/instances/{name}')
        except exceptions.ProvisionError as e:
            if '404' in str(e) or 'not found' in str(e).lower():
                return
            raise
        self.wait_zone_operation(zone, op)

    # ----- persistent disks (volumes.py gcp-disk type) -----------------------
    def create_disk(self, zone: str, name: str, size_gb: int,
                    disk_type: str = 'pd-balanced') -> None:
        op = self._request(
            'POST', f'{self._zone_path(zone)}/disks',
            body={
                'name': name,
                'sizeGb': str(size_gb),
                'type': f'{self._zone_path(zone)}/diskTypes/{disk_type}',
                'labels': {'skytpu-volume': name},
            })
        self.wait_zone_operation(zone, op)

    def get_disk(self, zone: str, name: str) -> Dict[str, Any]:
        return self._request('GET',
                             f'{self._zone_path(zone)}/disks/{name}')

    def delete_disk(self, zone: str, name: str) -> None:
        try:
            op = self._request(
                'DELETE', f'{self._zone_path(zone)}/disks/{name}')
        except exceptions.ProvisionError as e:
            if '404' in str(e) or 'not found' in str(e).lower():
                return
            raise
        self.wait_zone_operation(zone, op)

    def stop_instance(self, zone: str, name: str) -> None:
        op = self._request(
            'POST', f'{self._zone_path(zone)}/instances/{name}/stop')
        self.wait_zone_operation(zone, op)

    def start_instance(self, zone: str, name: str) -> None:
        op = self._request(
            'POST', f'{self._zone_path(zone)}/instances/{name}/start')
        self.wait_zone_operation(zone, op)

    def resume_instance(self, zone: str, name: str) -> None:
        """SUSPENDED instances need resume, not start."""
        op = self._request(
            'POST', f'{self._zone_path(zone)}/instances/{name}/resume')
        self.wait_zone_operation(zone, op)

    def wait_instance_status(self, zone: str, name: str, statuses,
                             timeout_s: float = 300.0) -> str:
        """Poll until the instance reaches one of `statuses` (e.g. a
        STOPPING instance settling into TERMINATED before a restart)."""
        deadline = time.time() + timeout_s
        backoff = common_utils.Backoff(initial=1.0, cap=10.0)
        while True:
            status = self.get_instance(zone, name).get('status')
            if status in statuses:
                return status
            if time.time() > deadline:
                raise exceptions.ProvisionError(
                    f'instance {name} stuck in {status}, wanted one of '
                    f'{statuses}')
            time.sleep(backoff.current_backoff())
