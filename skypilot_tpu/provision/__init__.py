"""Provider-agnostic provisioning API, routed by cloud name.

Parity: sky/provision/__init__.py:45 `_route_to_cloud_impl` — each function
dispatches to `skypilot_tpu.provision.<cloud>.instance`.
"""
from __future__ import annotations

import importlib
from typing import Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.utils import timeline
from skypilot_tpu.provision.common import (ClusterInfo, InstanceInfo,
                                           InstanceStatus, ProvisionConfig,
                                           ProvisionRecord)

__all__ = [
    'ClusterInfo', 'InstanceInfo', 'InstanceStatus', 'ProvisionConfig',
    'ProvisionRecord', 'run_instances', 'stop_instances',
    'terminate_instances', 'wait_instances', 'query_instances',
    'get_cluster_info', 'open_ports',
]


def _impl(cloud: str):
    try:
        return importlib.import_module(
            f'skypilot_tpu.provision.{cloud}.instance')
    except ImportError as e:
        raise exceptions.InvalidInfraError(
            f'No provisioner for cloud {cloud!r}.') from e


def run_instances(cloud: str, config: ProvisionConfig) -> ProvisionRecord:
    """Create (or resume) the cluster's nodes.  Blocks until the creation
    request is accepted, NOT until instances are running — call
    wait_instances next."""
    with timeline.Event('provision.run_instances', cloud=cloud,
                        cluster=config.cluster_name):
        return _impl(cloud).run_instances(config)


def stop_instances(cloud: str, cluster_name: str,
                   region: Optional[str] = None,
                   zone: Optional[str] = None) -> None:
    return _impl(cloud).stop_instances(cluster_name, region, zone)


def terminate_instances(cloud: str, cluster_name: str,
                        region: Optional[str] = None,
                        zone: Optional[str] = None) -> None:
    with timeline.Event('provision.terminate_instances', cloud=cloud,
                        cluster=cluster_name):
        return _impl(cloud).terminate_instances(cluster_name, region, zone)


def wait_instances(cloud: str, cluster_name: str,
                   region: Optional[str] = None,
                   zone: Optional[str] = None,
                   timeout_s: float = 1800.0) -> None:
    """Block until every node is RUNNING (raises on PREEMPTED/TERMINATED)."""
    with timeline.Event('provision.wait_instances', cloud=cloud,
                        cluster=cluster_name):
        return _impl(cloud).wait_instances(cluster_name, region, zone,
                                           timeout_s)


def query_instances(
        cloud: str, cluster_name: str,
        region: Optional[str] = None,
        zone: Optional[str] = None) -> Dict[str, InstanceStatus]:
    """instance_id → status; the status-reconciliation primitive
    (reference: backend_utils._update_cluster_status → query_instances)."""
    return _impl(cloud).query_instances(cluster_name, region, zone)


def get_cluster_info(cloud: str, cluster_name: str,
                     region: Optional[str] = None,
                     zone: Optional[str] = None) -> ClusterInfo:
    return _impl(cloud).get_cluster_info(cluster_name, region, zone)


def open_ports(cloud: str, cluster_name: str, ports: List[str],
               region: Optional[str] = None,
               zone: Optional[str] = None) -> None:
    impl = _impl(cloud)
    if hasattr(impl, 'open_ports'):
        impl.open_ports(cluster_name, ports, region, zone)
