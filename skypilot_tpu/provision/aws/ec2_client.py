"""EC2 client with two backends behind one narrow interface.

Real path: boto3 through the lazy adaptor (adaptors/aws.py) — the same
surface the reference drives via boto3 in sky/provision/aws/instance.py.
Fake path: with ``SKYTPU_EC2_API_ENDPOINT`` set, a plain JSON/HTTP
protocol against tests/fake_ec2_api.py (sibling of the fake GCE/TPU
servers) so the whole provisioner is testable hermetically — the same
pattern the GCE client uses (provision/gcp/gce_client.py).

The interface is deliberately tiny: instances are identified by their
``Name`` tag (``<cluster>-<i>``) and grouped by a ``skytpu-cluster`` tag,
mirroring the label scheme of the GCP provisioners.

Error taxonomy (feeds the failover blocklists, provision/failover.py):
  InsufficientInstanceCapacity / SpotMaxPriceTooLow -> stockout (zone)
  VcpuLimitExceeded / *LimitExceeded               -> quota (region)
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions

CLUSTER_TAG = 'skytpu-cluster'

_STOCKOUT_CODES = ('InsufficientInstanceCapacity', 'SpotMaxPriceTooLow',
                   'InsufficientHostCapacity')
_QUOTA_CODES = ('VcpuLimitExceeded', 'MaxSpotInstanceCountExceeded',
                'InstanceLimitExceeded')


def classify_aws_error(code: str, message: str) -> Exception:
    """AWS error code -> typed provision error (reference analog:
    FailoverCloudErrorHandlerV2._aws_handler)."""
    if any(code.startswith(c) or c in message for c in _QUOTA_CODES):
        return exceptions.QuotaExceededError(f'{code}: {message}')
    if any(code.startswith(c) for c in _STOCKOUT_CODES):
        return exceptions.InsufficientCapacityError(f'{code}: {message}')
    return exceptions.ProvisionError(f'EC2 error {code}: {message}')


class Ec2Client:
    """Narrow EC2 surface: run/describe/terminate/stop/start by Name tag."""

    def __init__(self, region: str) -> None:
        self.region = region
        self._fake_endpoint = os.environ.get('SKYTPU_EC2_API_ENDPOINT')

    # ----- fake transport ----------------------------------------------------
    def _fake(self, method: str, path: str,
              body: Optional[Dict[str, Any]] = None,
              params: Optional[Dict[str, str]] = None) -> Any:
        import requests
        url = f'{self._fake_endpoint.rstrip("/")}{path}'
        resp = requests.request(method, url, json=body, params=params,
                                timeout=30)
        if resp.status_code >= 400:
            err = resp.json().get('error', {})
            raise classify_aws_error(err.get('code', str(resp.status_code)),
                                     err.get('message', resp.text))
        return resp.json() if resp.text else {}

    # ----- real transport ----------------------------------------------------
    def _boto(self):
        from skypilot_tpu.adaptors import aws as aws_adaptor
        return aws_adaptor.client('ec2', region=self.region)

    def _boto_call(self, fn_name: str, **kwargs) -> Any:
        client = self._boto()
        try:
            return getattr(client, fn_name)(**kwargs)
        except Exception as e:  # pylint: disable=broad-except
            code = getattr(e, 'response', {}).get(
                'Error', {}).get('Code', '')
            if code:
                raise classify_aws_error(code, str(e)) from e
            raise

    # ----- operations --------------------------------------------------------
    def run_instances(self, cluster_name: str, names: List[str],
                      instance_type: str, zone: Optional[str] = None,
                      use_spot: bool = False,
                      image_id: Optional[str] = None,
                      user_data: Optional[str] = None) -> List[Dict]:
        """Create one instance per name (idempotence is the caller's job:
        pass only the names that do not already exist)."""
        created = []
        for name in names:
            tags = [{'Key': 'Name', 'Value': name},
                    {'Key': CLUSTER_TAG, 'Value': cluster_name}]
            if self._fake_endpoint:
                inst = self._fake('POST', '/run_instances', body={
                    'region': self.region, 'zone': zone, 'name': name,
                    'cluster': cluster_name,
                    'instance_type': instance_type,
                    'use_spot': use_spot, 'image_id': image_id,
                })['instance']
            else:
                kwargs: Dict[str, Any] = dict(
                    MinCount=1, MaxCount=1, InstanceType=instance_type,
                    TagSpecifications=[{'ResourceType': 'instance',
                                        'Tags': tags}])
                if image_id:
                    kwargs['ImageId'] = image_id
                if zone:
                    kwargs['Placement'] = {'AvailabilityZone': zone}
                if use_spot:
                    kwargs['InstanceMarketOptions'] = {'MarketType': 'spot'}
                if user_data:
                    kwargs['UserData'] = user_data
                resp = self._boto_call('run_instances', **kwargs)
                inst = self._to_dict(resp['Instances'][0], name)
            created.append(inst)
        return created

    def list_instances(self, cluster_name: str) -> List[Dict]:
        """All non-terminated instances tagged with this cluster."""
        if self._fake_endpoint:
            return self._fake('GET', '/instances', params={
                'region': self.region, 'cluster': cluster_name,
            })['instances']
        resp = self._boto_call(
            'describe_instances',
            Filters=[{'Name': f'tag:{CLUSTER_TAG}',
                      'Values': [cluster_name]},
                     {'Name': 'instance-state-name',
                      'Values': ['pending', 'running', 'stopping',
                                 'stopped', 'shutting-down']}])
        out = []
        for resv in resp.get('Reservations', []):
            for inst in resv.get('Instances', []):
                name = next((t['Value'] for t in inst.get('Tags', [])
                             if t['Key'] == 'Name'), inst['InstanceId'])
                out.append(self._to_dict(inst, name))
        return out

    def _ids_for(self, cluster_name: str,
                 names: Optional[List[str]] = None) -> List[str]:
        return [i['instance_id'] for i in self.list_instances(cluster_name)
                if names is None or i['name'] in names]

    def terminate(self, cluster_name: str,
                  names: Optional[List[str]] = None) -> None:
        if self._fake_endpoint:
            self._fake('POST', '/terminate', body={
                'region': self.region, 'cluster': cluster_name,
                'names': names})
            return
        ids = self._ids_for(cluster_name, names)
        if ids:
            self._boto_call('terminate_instances', InstanceIds=ids)

    def stop(self, cluster_name: str) -> None:
        if self._fake_endpoint:
            self._fake('POST', '/stop', body={'region': self.region,
                                              'cluster': cluster_name})
            return
        ids = self._ids_for(cluster_name)
        if ids:
            self._boto_call('stop_instances', InstanceIds=ids)

    def start(self, cluster_name: str,
              names: Optional[List[str]] = None) -> None:
        if self._fake_endpoint:
            self._fake('POST', '/start', body={'region': self.region,
                                               'cluster': cluster_name,
                                               'names': names})
            return
        ids = self._ids_for(cluster_name, names)
        if ids:
            self._boto_call('start_instances', InstanceIds=ids)

    @staticmethod
    def _to_dict(inst: Dict[str, Any], name: str) -> Dict[str, Any]:
        return {
            'instance_id': inst.get('InstanceId'),
            'name': name,
            'state': inst.get('State', {}).get('Name', 'pending'),
            'public_ip': inst.get('PublicIpAddress'),
            'private_ip': inst.get('PrivateIpAddress'),
            'zone': inst.get('Placement', {}).get('AvailabilityZone'),
        }
