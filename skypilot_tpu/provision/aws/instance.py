"""AWS EC2 provisioner (parity: sky/provision/aws/instance.py).

Same contract as the GCP provisioners (provision/gcp/instance.py):
instances are named ``<cluster>-<i>``, tagged with ``skytpu-cluster``,
reused when already running, restarted when stopped, re-created when
terminated.  Region-scoped (EC2 placement is per-AZ but the API is
regional); ``zone`` pins an availability zone when given.

The transport is Ec2Client (ec2_client.py): boto3 for real AWS, a JSON
fake (tests/fake_ec2_api.py) under SKYTPU_EC2_API_ENDPOINT — the whole
lifecycle is hermetically testable like the GCE/TPU paths.
"""
from __future__ import annotations

import os
import time
from typing import Dict, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import sky_logging
from skypilot_tpu.provision import common
from skypilot_tpu.provision.aws import ec2_client as ec2_client_lib

logger = sky_logging.init_logger(__name__)

# EC2 instance states -> framework InstanceStatus.  A spot-interrupted
# instance surfaces as 'terminated' in describe results; list_instances
# keeps 'shutting-down' visible so reconciliation can observe it.
_STATE_MAP = {
    'pending': common.InstanceStatus.PENDING,
    'running': common.InstanceStatus.RUNNING,
    'stopping': common.InstanceStatus.STOPPED,
    'stopped': common.InstanceStatus.STOPPED,
    'shutting-down': common.InstanceStatus.TERMINATED,
    'terminated': common.InstanceStatus.TERMINATED,
}


def _node_id(cluster_name: str, index: int) -> str:
    return f'{cluster_name}-{index}'


def _client(region: Optional[str]) -> ec2_client_lib.Ec2Client:
    if not region:
        raise exceptions.ProvisionError('AWS provisioning needs a region.')
    return ec2_client_lib.Ec2Client(region)


def _poll_s(default: float = 5.0) -> float:
    return float(os.environ.get('SKYTPU_PROVISION_POLL_S', default))


def run_instances(config: common.ProvisionConfig) -> common.ProvisionRecord:
    res = resources_lib.Resources.from_yaml_config(config.resources_config)
    instance_type = res.instance_type
    if instance_type is None:
        from skypilot_tpu.catalog import aws_catalog
        instance_type = aws_catalog.get_default_instance_type(
            res.cpus, res.memory)
    if instance_type is None:
        raise exceptions.ProvisionError(
            f'no EC2 instance type satisfies cpus={res.cpus} '
            f'memory={res.memory}')
    client = _client(config.region)
    existing = {i['name']: i for i in
                client.list_instances(config.cluster_name)}
    instance_ids = []
    to_create = []
    to_start = []
    resumed = False
    for i in range(config.num_nodes):
        name = _node_id(config.cluster_name, i)
        instance_ids.append(name)
        inst = existing.get(name)
        state = inst['state'] if inst else None
        if state in ('running', 'pending'):
            resumed = True
            continue
        if state in ('stopped', 'stopping'):
            # Only in-range nodes (starting the whole cluster tag would
            # also resurrect nodes beyond num_nodes on a shrunk
            # relaunch), batched into ONE start call after the loop.
            to_start.append(name)
            resumed = True
            continue
        if state == 'shutting-down':
            # Terminating from a prior down: wait out, then re-create.
            deadline = time.time() + 120
            while time.time() < deadline:
                cur = {x['name']: x for x in
                       client.list_instances(config.cluster_name)}
                if name not in cur:
                    break
                time.sleep(_poll_s(2.0))
        to_create.append(name)
    if to_start:
        client.start(config.cluster_name, names=to_start)
    if to_create:
        user_data = None
        if config.authorized_key:
            user_data = ('#!/bin/bash\n'
                         'mkdir -p /home/skytpu/.ssh\n'
                         f'echo "{config.authorized_key}" >> '
                         '/home/skytpu/.ssh/authorized_keys\n')
        client.run_instances(config.cluster_name, to_create,
                             instance_type=instance_type,
                             zone=config.zone,
                             use_spot=res.use_spot,
                             image_id=(res.image_id
                                       if isinstance(res.image_id, str)
                                       else None),
                             user_data=user_data)
    return common.ProvisionRecord(
        provider_name='aws', cluster_name=config.cluster_name,
        region=config.region, zone=config.zone,
        instance_ids=instance_ids, resumed=resumed)


def wait_instances(cluster_name: str, region=None, zone=None,
                   timeout_s: float = 1800.0) -> None:
    del zone
    client = _client(region)
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        insts = client.list_instances(cluster_name)
        states = {i['name']: i['state'] for i in insts}
        if insts and all(s == 'running' for s in states.values()):
            return
        bad = {n: s for n, s in states.items() if s == 'terminated'}
        if bad:
            raise exceptions.ProvisionError(
                f'instances terminated while waiting: {bad}')
        time.sleep(_poll_s())
    raise exceptions.ProvisionError(
        f'timed out waiting for {cluster_name} instances: '
        f'{ {i["name"]: i["state"] for i in client.list_instances(cluster_name)} }')


def query_instances(cluster_name: str, region=None,
                    zone=None) -> Dict[str, common.InstanceStatus]:
    del zone
    client = _client(region)
    out: Dict[str, common.InstanceStatus] = {}
    for inst in client.list_instances(cluster_name):
        out[inst['name']] = _STATE_MAP.get(inst['state'],
                                           common.InstanceStatus.PENDING)
    return out


def stop_instances(cluster_name: str, region=None, zone=None) -> None:
    del zone
    _client(region).stop(cluster_name)


def terminate_instances(cluster_name: str, region=None, zone=None) -> None:
    del zone
    _client(region).terminate(cluster_name)


def get_cluster_info(cluster_name: str, region=None,
                     zone=None) -> common.ClusterInfo:
    del zone
    client = _client(region)
    instances = []
    insts = sorted(client.list_instances(cluster_name),
                   key=lambda i: i['name'])
    for inst in insts:
        instances.append(common.InstanceInfo(
            instance_id=inst['name'],
            internal_ips=[ip for ip in [inst.get('private_ip')] if ip],
            external_ips=[ip for ip in [inst.get('public_ip')] if ip],
            status=_STATE_MAP.get(inst['state'],
                                  common.InstanceStatus.PENDING),
            tags={},
        ))
    return common.ClusterInfo(provider_name='aws',
                              cluster_name=cluster_name,
                              instances=instances)
