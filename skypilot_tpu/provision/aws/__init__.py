"""AWS EC2 provisioner (parity: sky/provision/aws/)."""
