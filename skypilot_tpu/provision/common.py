"""Provision-layer data model (parity: sky/provision/common.py).

The unit of provisioning is the *node*: for TPU slices one node is one TPU
resource (which brings `num_hosts` host VMs with it — the API allocates them
atomically); for VM/local clouds one node is one instance.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Any, Dict, List, Optional


class InstanceStatus(enum.Enum):
    PENDING = 'PENDING'        # creating / queued
    RUNNING = 'RUNNING'
    STOPPED = 'STOPPED'
    PREEMPTED = 'PREEMPTED'    # spot reclaim; stale resource may linger
    TERMINATED = 'TERMINATED'


@dataclasses.dataclass
class InstanceInfo:
    instance_id: str
    status: InstanceStatus
    # One entry per host VM of this node (TPU pods: num_hosts entries).
    internal_ips: List[str] = dataclasses.field(default_factory=list)
    external_ips: List[str] = dataclasses.field(default_factory=list)
    tags: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ProvisionConfig:
    """Everything a provider needs to create the cluster's nodes."""
    cluster_name: str
    num_nodes: int
    resources_config: Dict[str, Any]      # Resources.to_yaml_config()
    region: Optional[str] = None
    zone: Optional[str] = None
    authorized_key: Optional[str] = None  # pubkey to inject for SSH
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    ports: List[str] = dataclasses.field(default_factory=list)
    # {mount_path: volume_name} — pre-validated named volumes
    # (skypilot_tpu/volumes.py): k8s renders PVC mounts, GCP attaches
    # the persistent disk at instance insert.
    volumes: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ProvisionRecord:
    """Result of run_instances (parity: reference ProvisionRecord)."""
    provider_name: str
    cluster_name: str
    region: Optional[str]
    zone: Optional[str]
    instance_ids: List[str]
    resumed: bool = False       # reused existing stopped/running nodes


@dataclasses.dataclass
class ClusterInfo:
    """Post-provision cluster description (parity: get_cluster_info)."""
    provider_name: str
    cluster_name: str
    instances: List[InstanceInfo] = dataclasses.field(default_factory=list)
    ssh_user: str = 'skytpu'
    ssh_port: int = 22
    # Provider-mandated key (ssh node pools: the pool's identity_file);
    # None = the framework's own generated key.
    ssh_key_path: Optional[str] = None

    @property
    def node_ips(self) -> List[List[str]]:
        """Per node, the host IPs (external preferred, internal fallback)."""
        out = []
        for inst in self.instances:
            ips = inst.external_ips or inst.internal_ips
            out.append(list(ips))
        return out

    @property
    def head_ip(self) -> Optional[str]:
        ips = self.node_ips
        return ips[0][0] if ips and ips[0] else None
