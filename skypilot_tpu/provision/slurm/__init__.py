"""Slurm allocation provisioner (parity: sky/provision for slurm)."""
