"""Slurm provisioner: one ALLOCATION per cluster (parity:
sky/clouds/slurm.py's allocation model, rebuilt on the framework's
provision API).

The allocation is held by a long-running sbatch job named
``skytpu-<cluster>``: `srun sleep infinity` keeps every node of the
allocation busy so Slurm cannot reclaim it between framework jobs (the
framework's OWN gang executor runs the real work over SSH — Slurm is
the node lease, not the job runner).  Mapping to the provision API:

  run_instances        sbatch -N num_nodes [-p region]
  wait_instances       squeue state PENDING (queued) -> RUNNING
  query_instances      squeue state -> one synthetic instance per node
  get_cluster_info     scontrol show job -> hostnames -> per-node hosts
  terminate_instances  scancel by job name
  stop_instances       NotSupportedError (no such lifecycle in Slurm)

All through the standard CLIs (sbatch/squeue/scancel/scontrol), so the
hermetic tests drive the REAL command construction against fake CLI
shims on PATH (tests/fake_slurm.py) — the same boundary style as the
fake HTTP control planes.
"""
from __future__ import annotations

import os
import subprocess
import time
from typing import Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.provision import common

logger = sky_logging.init_logger(__name__)

_JOB_PREFIX = 'skytpu-'

# Slurm job states -> framework InstanceStatus (applied to every node of
# the allocation: the allocation is atomic, nodes share its state).
_STATE_MAP = {
    'PENDING': common.InstanceStatus.PENDING,
    'CONFIGURING': common.InstanceStatus.PENDING,
    'RUNNING': common.InstanceStatus.RUNNING,
    'COMPLETING': common.InstanceStatus.TERMINATED,
    'COMPLETED': common.InstanceStatus.TERMINATED,
    'CANCELLED': common.InstanceStatus.TERMINATED,
    'FAILED': common.InstanceStatus.TERMINATED,
    'TIMEOUT': common.InstanceStatus.TERMINATED,
    'PREEMPTED': common.InstanceStatus.PREEMPTED,
    'NODE_FAIL': common.InstanceStatus.PREEMPTED,
}


def _run(argv: List[str]) -> str:
    # A wedged slurmctld must fail the provision attempt (and feed the
    # failover engine, which only catches ProvisionError subclasses),
    # not hang the controller tick forever.
    try:
        proc = subprocess.run(argv, capture_output=True, text=True,
                              check=False,
                              timeout=float(os.environ.get(
                                  'SKYTPU_SLURM_CMD_TIMEOUT_S', '120')))
    except subprocess.TimeoutExpired as e:
        raise exceptions.ProvisionError(
            f'slurm command timed out: {" ".join(argv)}') from e
    if proc.returncode != 0:
        msg = (proc.stderr or proc.stdout).strip()
        low = msg.lower()
        if 'queue' in low and 'limit' in low or 'qosmax' in low.replace(
                ' ', ''):
            raise exceptions.QuotaExceededError(msg)
        raise exceptions.ProvisionError(
            f'{argv[0]} failed (rc={proc.returncode}): {msg}')
    return proc.stdout


def _job_name(cluster_name: str) -> str:
    return f'{_JOB_PREFIX}{cluster_name}'


_DEAD_STATES = frozenset(
    s for s, mapped in _STATE_MAP.items()
    if mapped in (common.InstanceStatus.TERMINATED,
                  common.InstanceStatus.PREEMPTED))
_TERMINAL_STATES = frozenset(
    s for s, mapped in _STATE_MAP.items()
    if mapped is common.InstanceStatus.TERMINATED)


def _find_job(cluster_name: str,
              live_only: bool = False) -> Optional[Dict[str, str]]:
    """{'id':…, 'state':…} of the newest matching allocation job.

    Scoped to THE CURRENT USER (shared login nodes: another user's
    identically-named job must never be mistaken for ours).  Terminal
    states are always filtered client-side (real squeue keeps finished
    jobs visible for MinJobAge, ~5 min).  live_only additionally drops
    PREEMPTED/NODE_FAIL jobs — a provisioning call must submit a FRESH
    sbatch for those, while status reconciliation (live_only=False)
    must still SEE them to report the preemption."""
    import getpass
    out = _run(['squeue', '--name', _job_name(cluster_name),
                '--user', getpass.getuser(), '--noheader',
                '-o', '%i|%T'])
    drop = _DEAD_STATES if live_only else _TERMINAL_STATES
    jobs = []
    for line in out.splitlines():
        line = line.strip()
        if not line:
            continue
        job_id, state = line.split('|', 1)
        if state.strip() in drop:
            continue
        jobs.append({'id': job_id.strip(), 'state': state.strip()})
    return jobs[-1] if jobs else None


def run_instances(config: common.ProvisionConfig) -> common.ProvisionRecord:
    existing = _find_job(config.cluster_name, live_only=True)
    if existing is not None:
        # Reuse only a size-compatible allocation: Slurm cannot grow a
        # running job, so silently "resuming" a smaller allocation would
        # under-provision the gang.
        have = _requested_nodes(existing['id'])
        if have is not None and have != config.num_nodes:
            raise exceptions.ProvisionError(
                f'live slurm allocation for {config.cluster_name!r} has '
                f'{have} nodes but {config.num_nodes} were requested; '
                f'`down` the cluster first (allocations cannot resize)')
    if existing is None:
        argv = ['sbatch', '--parsable',
                '--job-name', _job_name(config.cluster_name),
                '-N', str(config.num_nodes),
                '--wrap', 'srun sleep infinity']
        if config.region and config.region != 'default':
            argv += ['-p', config.region]
        job_id = _run(argv).strip().split(';')[0]
        logger.info(f'slurm allocation {job_id} requested for '
                    f'{config.cluster_name!r} ({config.num_nodes} nodes)')
        resumed = False
    else:
        job_id = existing['id']
        resumed = True
    return common.ProvisionRecord(
        provider_name='slurm', cluster_name=config.cluster_name,
        region=config.region, zone=None,
        instance_ids=[f'{config.cluster_name}-{i}'
                      for i in range(config.num_nodes)],
        resumed=resumed)


def _poll_s(default: float = 5.0) -> float:
    return float(os.environ.get('SKYTPU_PROVISION_POLL_S', default))


def wait_instances(cluster_name: str, region=None, zone=None,
                   timeout_s: float = 1800.0) -> None:
    del region, zone
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        job = _find_job(cluster_name, live_only=True)
        if job is None:
            raise exceptions.ProvisionError(
                f'slurm allocation for {cluster_name!r} disappeared '
                f'while waiting (cancelled or preempted)')
        status = _STATE_MAP.get(job['state'],
                                common.InstanceStatus.PENDING)
        if status is common.InstanceStatus.RUNNING:
            return
        if status in (common.InstanceStatus.TERMINATED,
                      common.InstanceStatus.PREEMPTED):
            raise exceptions.ProvisionError(
                f'slurm allocation for {cluster_name!r} ended while '
                f'waiting: {job["state"]}')
        time.sleep(_poll_s())
    raise exceptions.ProvisionError(
        f'timed out waiting for slurm allocation of {cluster_name!r}')


def _job_details(job_id: str) -> 'tuple[List[str], Optional[int]]':
    """(hostnames, requested_node_count) from ONE scontrol invocation.

    Hostnames are [] while PENDING (real Slurm reports NodeList=(null)
    until placement); NumNodes is present either way."""
    out = _run(['scontrol', 'show', 'job', job_id])
    nodelist = None
    num_nodes: Optional[int] = None
    for token in out.replace('\n', ' ').split():
        if token.startswith('NodeList=') and not token.startswith(
                'NodeList=(null)'):
            nodelist = token.split('=', 1)[1]
        elif token.startswith('NumNodes='):
            # Real scontrol can print a range ('2-2'); take the floor.
            value = token.split('=', 1)[1].split('-')[0]
            try:
                num_nodes = int(value)
            except ValueError:
                pass
    hosts: List[str] = []
    if nodelist:
        raw = _run(['scontrol', 'show', 'hostnames', nodelist])
        hosts = [h.strip() for h in raw.splitlines() if h.strip()]
    return hosts, num_nodes


def _nodes(job_id: str) -> List[str]:
    return _job_details(job_id)[0]


def _requested_nodes(job_id: str) -> Optional[int]:
    return _job_details(job_id)[1]


def query_instances(cluster_name: str, region=None,
                    zone=None) -> Dict[str, common.InstanceStatus]:
    del region, zone
    job = _find_job(cluster_name)
    if job is None:
        return {}
    status = _STATE_MAP.get(job['state'], common.InstanceStatus.PENDING)
    if status is common.InstanceStatus.TERMINATED:
        return {}
    # A PENDING allocation has no NodeList yet; size from NumNodes so a
    # queued 2-node cluster reports BOTH nodes pending, not one.
    hosts, requested = _job_details(job['id'])
    n = len(hosts) or requested or 1
    return {f'{cluster_name}-{i}': status for i in range(n)}


def stop_instances(cluster_name: str, region=None, zone=None) -> None:
    raise exceptions.NotSupportedError(
        'Slurm allocations cannot be stopped; `down` (scancel) releases '
        'them')


def terminate_instances(cluster_name: str, region=None,
                        zone=None) -> None:
    del region, zone
    job = _find_job(cluster_name)
    if job is not None:
        _run(['scancel', job['id']])


def get_cluster_info(cluster_name: str, region=None,
                     zone=None) -> common.ClusterInfo:
    del region, zone
    job = _find_job(cluster_name)
    instances = []
    if job is not None:
        status = _STATE_MAP.get(job['state'],
                                common.InstanceStatus.PENDING)
        for i, host in enumerate(_nodes(job['id'])):
            instances.append(common.InstanceInfo(
                instance_id=f'{cluster_name}-{i}',
                internal_ips=[host], external_ips=[host],
                status=status, tags={'slurm_job_id': job['id']}))
    import getpass
    # BYO identity: HPC sites share $HOME; the user's own SSH key works
    # and the framework key is never injected (ssh-pool semantics).
    return common.ClusterInfo(provider_name='slurm',
                              cluster_name=cluster_name,
                              instances=instances,
                              ssh_user=getpass.getuser(),
                              ssh_key_path=None)
