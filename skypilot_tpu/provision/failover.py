"""Stockout failover engine (parity: RetryingVmProvisioner,
cloud_vm_ray_backend.py:729).

Walks the optimizer's cheapest-first candidate placements; on a typed
provision failure it blocklists the zone (stockout) or the whole region
(quota — reference blocklist semantics, cloud_vm_ray_backend.py:325), then
re-optimizes with the accumulated blocklist and tries the next placement.
Each failure is recorded in the failover history surfaced to the user on
final failure.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import task as task_lib
from skypilot_tpu.optimizer import Optimizer, OptimizeTarget
from skypilot_tpu.provision import common as provision_common
from skypilot_tpu import dag as dag_lib
from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)


@dataclasses.dataclass
class ProvisionAttemptResult:
    record: provision_common.ProvisionRecord
    resources: resources_lib.Resources


def _blocklist_entry(
        candidate: resources_lib.Resources,
        blocklist_region: bool) -> resources_lib.Resources:
    """Resources pattern to block: zone-level by default, region-level for
    quota errors."""
    infra = f'{candidate.cloud}/{candidate.region}'
    if not blocklist_region and candidate.zone:
        infra += f'/{candidate.zone}'
    return resources_lib.Resources.from_yaml_config({'infra': infra})


def provision_with_retries(
    task: task_lib.Task,
    cluster_name: str,
    provision_fn: Callable[[resources_lib.Resources],
                           provision_common.ProvisionRecord],
    max_attempts: int = 16,
    blocked_resources: Optional[List[resources_lib.Resources]] = None,
    cleanup_fn: Optional[Callable[[resources_lib.Resources], None]] = None,
) -> ProvisionAttemptResult:
    """Try placements until one provisions.

    provision_fn(candidate) must raise a typed ProvisionError subclass on
    failure; its `blocklist_region` attribute chooses the blocklist scope.
    The task is re-optimized (cheapest surviving placement) between
    attempts — the reference does the same full re-plan per retry round.
    cleanup_fn(candidate) runs after every failed attempt so partially-
    provisioned nodes / parked queued-resources in the failed zone are
    deleted before failing over (otherwise a later-ACTIVE queued resource
    materializes a billed slice no teardown path can reach).
    """
    blocked: List[resources_lib.Resources] = list(blocked_resources or [])
    history: List[Exception] = []
    for attempt in range(max_attempts):
        single = dag_lib.dag_from_task(task)
        try:
            Optimizer.optimize(single, minimize=OptimizeTarget.COST,
                               blocked_resources=blocked, quiet=True)
        except exceptions.ResourcesUnavailableError as e:
            raise exceptions.ResourcesUnavailableError(
                f'Provisioning {cluster_name!r} failed after exhausting '
                f'all placements ({attempt} attempts).\n'
                + exceptions.format_failover_history(history)
            ).with_failover_history(history) from e
        candidate = task.best_resources
        assert candidate is not None
        try:
            record = provision_fn(candidate)
            return ProvisionAttemptResult(record, candidate)
        except exceptions.ProvisionError as e:
            history.append(e)
            if cleanup_fn is not None:
                try:
                    cleanup_fn(candidate)
                except Exception as cleanup_err:  # pylint: disable=broad-except
                    logger.warning(
                        f'cleanup after failed attempt in '
                        f'{candidate.zone} failed: {cleanup_err}')
            entry = _blocklist_entry(candidate, e.blocklist_region)
            blocked.append(entry)
            scope = 'region' if e.blocklist_region else 'zone'
            logger.warning(
                f'Provision attempt {attempt + 1} in '
                f'{candidate.region}/{candidate.zone} failed '
                f'({type(e).__name__}); blocklisting {scope} and '
                f'failing over.')
    raise exceptions.ResourcesUnavailableError(
        f'Provisioning {cluster_name!r} failed: {max_attempts} attempts '
        f'exhausted.\n' + exceptions.format_failover_history(history)
    ).with_failover_history(history)
