"""Stockout failover engine (parity: RetryingVmProvisioner,
cloud_vm_ray_backend.py:729).

Walks the optimizer's cheapest-first candidate placements; on a typed
provision failure it blocklists the zone (stockout) or the whole region
(quota — reference blocklist semantics, cloud_vm_ray_backend.py:325), then
re-optimizes with the accumulated blocklist and tries the next placement.
Each failure is recorded in the failover history surfaced to the user on
final failure.

`retry_until_up` (reference: `sky launch --retry-until-up`,
provision_with_retries looping at cloud_vm_ray_backend.py:1638): when one
full sweep over every placement fails, forget the sweep's stockout
blocklist (capacity comes and goes), sleep a gap, and sweep again —
forever, until something provisions.
"""
from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import task as task_lib
from skypilot_tpu.optimizer import Optimizer, OptimizeTarget
from skypilot_tpu.provision import common as provision_common
from skypilot_tpu import dag as dag_lib
from skypilot_tpu import sky_logging
from skypilot_tpu.utils import timeline

logger = sky_logging.init_logger(__name__)


@dataclasses.dataclass
class ProvisionAttemptResult:
    record: provision_common.ProvisionRecord
    resources: resources_lib.Resources


def _blocklist_entry(
        candidate: resources_lib.Resources,
        blocklist_region: bool) -> resources_lib.Resources:
    """Resources pattern to block: zone-level by default, region-level for
    quota errors."""
    infra = f'{candidate.cloud}/{candidate.region}'
    if not blocklist_region and candidate.zone:
        infra += f'/{candidate.zone}'
    return resources_lib.Resources.from_yaml_config({'infra': infra})


def retry_gap_seconds() -> float:
    """Sleep between retry_until_up sweeps (reference waits a gap before
    re-sweeping placements)."""
    return float(os.environ.get('SKYTPU_RETRY_UNTIL_UP_GAP_S', '60'))


def provision_with_retries(
    task: task_lib.Task,
    cluster_name: str,
    provision_fn: Callable[[resources_lib.Resources],
                           provision_common.ProvisionRecord],
    max_attempts: int = 16,
    blocked_resources: Optional[List[resources_lib.Resources]] = None,
    cleanup_fn: Optional[Callable[[resources_lib.Resources], None]] = None,
    retry_until_up: bool = False,
    max_rounds: Optional[int] = None,
    minimize: OptimizeTarget = OptimizeTarget.COST,
) -> ProvisionAttemptResult:
    """Try placements until one provisions.

    provision_fn(candidate) must raise a typed ProvisionError subclass on
    failure; its `blocklist_region` attribute chooses the blocklist scope.
    The task is re-optimized (cheapest surviving placement) between
    attempts — the reference does the same full re-plan per retry round.
    cleanup_fn(candidate) runs after every failed attempt so partially-
    provisioned nodes / parked queued-resources in the failed zone are
    deleted before failing over (otherwise a later-ACTIVE queued resource
    materializes a billed slice no teardown path can reach).

    retry_until_up: instead of raising when a sweep exhausts every
    placement, drop the sweep's blocklist (quota blocks persist — quota
    does not free itself the way capacity does), sleep retry_gap_seconds()
    and sweep again.  max_rounds bounds this for tests; None = forever.
    """
    permanent: List[resources_lib.Resources] = list(blocked_resources or [])
    round_no = 0
    history: List[Exception] = []   # accumulated across ALL rounds
    while True:
        round_no += 1
        blocked = list(permanent)
        exhausted: Optional[Exception] = None
        for attempt in range(max_attempts):
            single = dag_lib.dag_from_task(task)
            try:
                Optimizer.optimize(single, minimize=minimize,
                                   blocked_resources=blocked, quiet=True)
            except exceptions.ResourcesUnavailableError as e:
                exhausted = e
                break
            candidate = task.best_resources
            assert candidate is not None
            try:
                with timeline.Event('failover.attempt',
                                    region=str(candidate.region),
                                    zone=str(candidate.zone)):
                    record = provision_fn(candidate)
                return ProvisionAttemptResult(record, candidate)
            except exceptions.ProvisionError as e:
                history.append(e)
                if cleanup_fn is not None:
                    try:
                        cleanup_fn(candidate)
                    except Exception as cleanup_err:  # pylint: disable=broad-except
                        logger.warning(
                            f'cleanup after failed attempt in '
                            f'{candidate.zone} failed: {cleanup_err}')
                entry = _blocklist_entry(candidate, e.blocklist_region)
                blocked.append(entry)
                if e.blocklist_region:
                    # Quota: permanent across retry_until_up rounds.
                    permanent.append(entry)
                scope = 'region' if e.blocklist_region else 'zone'
                logger.warning(
                    f'Provision attempt {attempt + 1} in '
                    f'{candidate.region}/{candidate.zone} failed '
                    f'({type(e).__name__}); blocklisting {scope} and '
                    f'failing over.')
        # A round that never attempted anything means every placement is
        # permanently blocked (quota) — waiting cannot help; raise even
        # under retry_until_up.
        nothing_attemptable = (exhausted is not None and
                               len(blocked) == len(permanent))
        if not retry_until_up or nothing_attemptable or \
                (max_rounds is not None and round_no >= max_rounds):
            n = len(history)
            raise exceptions.ResourcesUnavailableError(
                f'Provisioning {cluster_name!r} failed after exhausting '
                f'all placements ({n} attempts'
                f'{f", {round_no} rounds" if round_no > 1 else ""}).\n'
                + exceptions.format_failover_history(history)
            ).with_failover_history(history) from exhausted
        gap = retry_gap_seconds()
        logger.warning(
            f'retry_until_up: round {round_no} exhausted every placement '
            f'for {cluster_name!r}; retrying in {gap:.0f}s.')
        time.sleep(gap)
