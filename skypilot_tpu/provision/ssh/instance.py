"""SSH-pool provisioner: allocation instead of creation (parity:
sky/ssh_node_pools behind the generic provision API).

"Provisioning" reserves free hosts from the named pool
(skypilot_tpu/ssh_node_pools.py); nothing is created or destroyed.
Liveness is a TCP probe of the SSH port — an unreachable host reports
TERMINATED so the status reconciler and managed-jobs recovery see dead
machines the same way they see deleted VMs.
"""
from __future__ import annotations

import socket
from typing import Dict, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import ssh_node_pools
from skypilot_tpu.provision import common


def _pool(region: Optional[str]) -> str:
    if not region:
        raise exceptions.InvalidInfraError(
            'ssh provisioning needs a pool: use infra ssh/<pool>')
    return region


def _host_alive(host: str, port: int = 22, timeout_s: float = 2.0) -> bool:
    try:
        with socket.create_connection((host, port), timeout=timeout_s):
            return True
    except OSError:
        return False


def run_instances(config: common.ProvisionConfig) -> common.ProvisionRecord:
    pool = _pool(config.region)
    existing = ssh_node_pools.allocation(pool, config.cluster_name)
    hosts = ssh_node_pools.allocate(pool, config.cluster_name,
                                    config.num_nodes)
    return common.ProvisionRecord('ssh', config.cluster_name, pool, None,
                                  hosts, resumed=bool(existing))


def stop_instances(cluster_name: str, region=None, zone=None) -> None:
    raise exceptions.NotSupportedError(
        'ssh pool hosts are always on; down releases them')


def terminate_instances(cluster_name: str, region=None, zone=None) -> None:
    ssh_node_pools.release(_pool(region), cluster_name)


def wait_instances(cluster_name: str, region=None, zone=None,
                   timeout_s: float = 1800.0) -> None:
    del timeout_s
    statuses = query_instances(cluster_name, region, zone)
    dead = [h for h, s in statuses.items()
            if s is not common.InstanceStatus.RUNNING]
    if dead:
        # Release so the failover engine can try another pool; dead
        # hosts stay in the pool file for the operator to fix.
        ssh_node_pools.release(_pool(region), cluster_name)
        raise exceptions.InsufficientCapacityError(
            f'ssh hosts unreachable on port 22: {dead}')


def query_instances(cluster_name: str, region=None,
                    zone=None) -> Dict[str, common.InstanceStatus]:
    pool = _pool(region)
    port = ssh_node_pools.get_pool(pool)['port']
    out: Dict[str, common.InstanceStatus] = {}
    for host in ssh_node_pools.allocation(pool, cluster_name):
        out[host] = (common.InstanceStatus.RUNNING
                     if _host_alive(host, port)
                     else common.InstanceStatus.TERMINATED)
    return out


def get_cluster_info(cluster_name: str, region=None,
                     zone=None) -> common.ClusterInfo:
    pool = _pool(region)
    cfg = ssh_node_pools.get_pool(pool)
    instances = [
        common.InstanceInfo(
            instance_id=host,
            status=(common.InstanceStatus.RUNNING
                    if _host_alive(host, cfg['port'])
                    else common.InstanceStatus.TERMINATED),
            internal_ips=[host],
            external_ips=[],
        )
        for host in ssh_node_pools.allocation(pool, cluster_name)
    ]
    return common.ClusterInfo('ssh', cluster_name, instances,
                              ssh_user=cfg['user'], ssh_port=cfg['port'],
                              ssh_key_path=cfg.get('identity_file'))
