"""Docker task runtime for VM hosts (parity: sky/provision/docker_utils.py
— the reference initializes a privileged container on each VM and runs
the task inside it; TPU VMs need --privileged for /dev/accel* access,
sky/clouds/gcp.py:545-546).

A task requests it with `image_id: docker:<image>` on VM-backed
resources (pods use the image directly — provision/kubernetes).  Flow:

- `bootstrap_command(image)` runs once per host at job-setup time: pull
  the image and start a long-lived container (sleep infinity) named
  `skytpu-ct`, with host networking (the gang rank env points peers at
  host IPs; the JAX coordinator must be reachable on them), /dev and
  the workdir bind-mounted, and --privileged so TPU device nodes work.
  Idempotent: an existing container of the same image is reused, a
  stale one (different image) is replaced.
- `wrap(cmd, env)` turns a host command into `docker exec` inside that
  container, exporting the env INSIDE the container (the gang's rank /
  coordinator contract must reach the task, not the docker client).
"""
from __future__ import annotations

import shlex
from typing import Dict, Optional

CONTAINER_NAME = 'skytpu-ct'
DOCKER_PREFIX = 'docker:'


def image_from_resources(image_id: Optional[str]) -> Optional[str]:
    """The docker image a task asked for, or None (plain-VM task)."""
    if image_id and image_id.startswith(DOCKER_PREFIX):
        return image_id[len(DOCKER_PREFIX):]
    return None


def bootstrap_command(image: str,
                      workdir: Optional[str] = None) -> str:
    """Idempotent per-host container bootstrap (pull + run-or-reuse)."""
    img = shlex.quote(image)
    name = CONTAINER_NAME
    mounts = '-v /dev:/dev'
    workdir_flag = ''
    if workdir:
        wd = shlex.quote(workdir)
        mounts += f' -v {wd}:{wd}'
        workdir_flag = f'-w {wd} '
    return (
        # Reuse only a RUNNING container of the same image (a matching
        # but Exited one — host reboot, daemon restart — would make
        # every later docker exec fail); replace anything else.
        f'CUR=$(docker inspect '
        f'-f "{{{{.Config.Image}}}} {{{{.State.Running}}}}" {name} '
        f'2>/dev/null || true); '
        f'if [ "$CUR" != {shlex.quote(f"{image} true")} ]; then '
        f'  docker rm -f {name} >/dev/null 2>&1 || true; '
        f'  docker pull {img} && '
        f'  docker run -d --privileged --network=host --name {name} '
        f'  {mounts} {workdir_flag}{img} sleep infinity; '
        f'fi')


def wrap(cmd: str, env: Optional[Dict[str, str]] = None,
         workdir: Optional[str] = None) -> str:
    """Host command -> the same command inside the task container.

    Env is exported inside the container (docker exec -e would also
    work, but an export prefix keeps quoting uniform with the SSH
    runner's remote wrapper, utils/command_runner.py _remote_cmd)."""
    prefix = ''
    if env:
        prefix = ' && '.join(
            f'export {k}={shlex.quote(str(v))}' for k, v in env.items())
        prefix += ' && '
    if workdir:
        prefix += f'cd {shlex.quote(workdir)} && '
    return (f'docker exec {CONTAINER_NAME} '
            f'bash -c {shlex.quote(prefix + cmd)}')
