"""Local provisioner: nodes are directories + state files on this machine.

The hermetic analog of the reference's mocked-cloud test path and
LocalDockerBackend: the full launch pipeline (provision → bootstrap → gang
execute → logs → down) runs against it with no cloud account, and tests
inject preemptions by flipping a node's state file — the same failure
surface query_instances exposes for real TPU slices.

Simulated TPU pods: a node whose resources request a multi-host slice gets
`num_hosts` host entries (same fan-out the gang executor sees on GCP).
"""
from __future__ import annotations

import json
import os
import shutil
import time
from typing import Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import resources as resources_lib
from skypilot_tpu.provision import common


def _root() -> str:
    return os.path.expanduser(
        os.environ.get('SKYTPU_LOCAL_INSTANCE_DIR',
                       '~/.skytpu/local_instances'))


def _cluster_dir(cluster_name: str) -> str:
    return os.path.join(_root(), cluster_name)


def _node_state_path(cluster_name: str, node_id: str) -> str:
    return os.path.join(_cluster_dir(cluster_name), node_id, 'state.json')


def _write_state(cluster_name: str, node_id: str, state: dict) -> None:
    path = _node_state_path(cluster_name, node_id)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, 'w', encoding='utf-8') as f:
        json.dump(state, f)


def _read_states(cluster_name: str) -> Dict[str, dict]:
    cdir = _cluster_dir(cluster_name)
    out = {}
    if not os.path.isdir(cdir):
        return out
    # Numeric order ('node-10' after 'node-2'): rank assignment and head
    # selection derive from this ordering.
    def _key(node_id: str):
        suffix = node_id.rsplit('-', 1)[-1]
        return (int(suffix) if suffix.isdigit() else 1 << 30, node_id)

    for node_id in sorted(os.listdir(cdir), key=_key):
        path = _node_state_path(cluster_name, node_id)
        if os.path.exists(path):
            with open(path, encoding='utf-8') as f:
                out[node_id] = json.load(f)
    return out


def run_instances(config: common.ProvisionConfig) -> common.ProvisionRecord:
    res = resources_lib.Resources.from_yaml_config(
        dict(config.resources_config))
    hosts_per_node = res.hosts_per_node
    existing = _read_states(config.cluster_name)
    instance_ids = []
    resumed = bool(existing)
    for i in range(config.num_nodes):
        node_id = f'node-{i}'
        instance_ids.append(node_id)
        state = existing.get(node_id)
        if state is None or state['status'] in ('TERMINATED',):
            state = {
                'status': 'RUNNING',
                'hosts': hosts_per_node,
                'created_at': time.time(),
            }
        else:
            state['status'] = 'RUNNING'
        _write_state(config.cluster_name, node_id, state)
    return common.ProvisionRecord('local', config.cluster_name, 'local',
                                  'local', instance_ids, resumed=resumed)


def stop_instances(cluster_name: str, region=None, zone=None) -> None:
    for node_id, state in _read_states(cluster_name).items():
        state['status'] = 'STOPPED'
        _write_state(cluster_name, node_id, state)


def terminate_instances(cluster_name: str, region=None, zone=None) -> None:
    cdir = _cluster_dir(cluster_name)
    if os.path.isdir(cdir):
        shutil.rmtree(cdir)
    # Real VM deletion destroys the disk; the local analog is the
    # cluster's agent home (job DB, logs, workdir, mounts).  Wiping it
    # keeps recovery tests honest: a re-provisioned local cluster starts
    # from nothing, and state survives only through external storage.
    agent_home = os.path.expanduser(f'~/.skytpu/agent-{cluster_name}')
    if os.path.isdir(agent_home):
        shutil.rmtree(agent_home, ignore_errors=True)


def wait_instances(cluster_name: str, region=None, zone=None,
                   timeout_s: float = 1800.0) -> None:
    statuses = query_instances(cluster_name)
    bad = {k: v for k, v in statuses.items()
           if v is not common.InstanceStatus.RUNNING}
    if bad:
        raise exceptions.ProvisionError(
            f'local nodes not running: {bad}')


def query_instances(cluster_name: str, region=None,
                    zone=None) -> Dict[str, common.InstanceStatus]:
    return {
        node_id: common.InstanceStatus(state['status'])
        for node_id, state in _read_states(cluster_name).items()
    }


def get_cluster_info(cluster_name: str, region=None,
                     zone=None) -> common.ClusterInfo:
    instances = []
    for node_id, state in _read_states(cluster_name).items():
        instances.append(
            common.InstanceInfo(
                instance_id=node_id,
                status=common.InstanceStatus(state['status']),
                internal_ips=['127.0.0.1'] * int(state.get('hosts', 1)),
                external_ips=[],
            ))
    import getpass
    return common.ClusterInfo('local', cluster_name, instances,
                              ssh_user=getpass.getuser())


# ----- test helpers (preemption injection) -----------------------------------
def inject_preemption(cluster_name: str, node_id: str = 'node-0') -> None:
    """Flip a node to PREEMPTED — the analog of the reference's smoke tests
    terminating instances mid-job (tests/smoke_tests/test_managed_job.py:355)."""
    states = _read_states(cluster_name)
    if node_id not in states:
        raise exceptions.ClusterDoesNotExistError(
            f'{cluster_name}/{node_id} not found')
    states[node_id]['status'] = 'PREEMPTED'
    _write_state(cluster_name, node_id, states[node_id])
