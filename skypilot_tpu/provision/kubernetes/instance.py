"""Kubernetes provisioner: one pod per node over the core v1 REST API
(parity: sky/provision/kubernetes/instance.py; GKE TPU shapes from
sky/provision/kubernetes/utils.py GKE_TPU_ACCELERATOR_TO_GENERATION).

Direct REST (no kubernetes client dependency): the surface used is four
endpoints — create/get/list/delete pod — authenticated by bearer token.
Endpoint resolution: SKYTPU_K8S_API_ENDPOINT env (tests point it at the
fake API server) else the current kubeconfig context's server.

TPU on GKE: a node requesting a TPU slice renders to GKE's TPU node
selectors (`cloud.google.com/gke-tpu-accelerator` + `-topology`) with
`google.com/tpu: <chips_per_host>` resource limits, one pod per slice
host — the same host fan-out the gang executor sees on a direct TPU VM
slice.  A pod stuck Unschedulable is this substrate's stockout: wait
classifies it as InsufficientCapacityError so the failover engine can
move on (other contexts / clouds).
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

import requests as requests_lib

from skypilot_tpu import exceptions
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import sky_logging
from skypilot_tpu.provision import common

logger = sky_logging.init_logger(__name__)

_LABEL_CLUSTER = 'skytpu-cluster'
_LABEL_NODE = 'skytpu-node'     # logical node (TPU slice) index
_LABEL_HOST = 'skytpu-host'     # host index within the node

# TPU generation -> GKE accelerator label value
# (sky/provision/kubernetes/utils.py GKE mapping).
GKE_TPU_ACCELERATOR = {
    'v4': 'tpu-v4-podslice',
    'v5litepod': 'tpu-v5-lite-podslice',
    'v5p': 'tpu-v5p-slice',
    'v6e': 'tpu-v6e-slice',
}


def _namespace() -> str:
    return os.environ.get('SKYTPU_K8S_NAMESPACE', 'default')


_kubeconfig_cache: dict = {}


def _kubeconfig_raw():
    """Parsed kubeconfig, cached by (path, mtime)."""
    path = os.path.expanduser(os.environ.get('KUBECONFIG', '~/.kube/config'))
    if not os.path.exists(path):
        return None
    try:
        key = (path, os.path.getmtime(path))
    except OSError:
        return None
    cached = _kubeconfig_cache.get('entry')
    if cached is not None and cached[0] == key:
        return cached[1]
    from skypilot_tpu.utils import common_utils
    try:
        cfg = common_utils.read_yaml(path)
    except Exception:  # pylint: disable=broad-except
        return None
    _kubeconfig_cache['entry'] = (key, cfg)
    return cfg


def current_context() -> Optional[str]:
    cfg = _kubeconfig_raw()
    return cfg.get('current-context') if cfg else None


def _kubeconfig(context: Optional[str]):
    """(server, token, ca_path) for `context` (current-context when
    None).  Minimal static-token kubeconfigs; exec-auth plugins are out
    of scope for this build.  certificate-authority-data is materialized
    to a file for requests' `verify=`."""
    cfg = _kubeconfig_raw()
    if cfg is None:
        return None, None, None
    try:
        name = context or cfg.get('current-context')
        ctx = next((c['context'] for c in cfg.get('contexts', [])
                    if c['name'] == name), None)
        if ctx is None:
            return None, None, None
        cluster = next((c['cluster'] for c in cfg.get('clusters', [])
                        if c['name'] == ctx['cluster']), {})
        user = next((u['user'] for u in cfg.get('users', [])
                     if u['name'] == ctx.get('user')), {})
        ca_path = None
        ca_data = cluster.get('certificate-authority-data')
        if ca_data:
            import base64
            import hashlib
            import tempfile
            digest = hashlib.sha256(ca_data.encode()).hexdigest()[:16]
            ca_path = os.path.join(tempfile.gettempdir(),
                                   f'skytpu-k8s-ca-{digest}.crt')
            if not os.path.exists(ca_path):
                with open(ca_path, 'wb') as f:
                    f.write(base64.b64decode(ca_data))
        elif cluster.get('certificate-authority'):
            ca_path = os.path.expanduser(
                cluster['certificate-authority'])
        return cluster.get('server'), user.get('token'), ca_path
    except Exception:  # pylint: disable=broad-except
        return None, None, None


class _Client:
    """Resolved API access for one context (the `region`)."""

    def __init__(self, context: Optional[str]) -> None:
        env = os.environ.get('SKYTPU_K8S_API_ENDPOINT')
        if env:
            self.base = env.rstrip('/')
            token = os.environ.get('SKYTPU_K8S_TOKEN')
            self.verify = True
        else:
            server, token, ca_path = _kubeconfig(context)
            if not server:
                raise exceptions.NoCloudAccessError(
                    f'No Kubernetes API endpoint for context '
                    f'{context or "<current>"!r}: set '
                    f'SKYTPU_K8S_API_ENDPOINT or provide a kubeconfig '
                    f'defining it.')
            self.base = server.rstrip('/')
            self.verify = ca_path if ca_path else True
        self.headers = {'Content-Type': 'application/json'}
        if token:
            self.headers['Authorization'] = f'Bearer {token}'

    def url(self, path: str) -> str:
        return f'{self.base}/api/v1/namespaces/{_namespace()}{path}'

    def request(self, method: str, path: str, **kwargs):
        try:
            return requests_lib.request(
                method, self.url(path), headers=self.headers,
                verify=self.verify, timeout=30, **kwargs)
        except requests_lib.RequestException as e:
            # Keep transport failures inside the provision-error
            # taxonomy (SSL/conn errors otherwise escape the failover
            # engine's classification).
            raise exceptions.ProvisionError(
                f'k8s API unreachable ({type(e).__name__}): {e}') from e


def _pod_name(cluster_name: str, index: int) -> str:
    return f'{cluster_name}-{index}'


def _pod_spec(config: common.ProvisionConfig, index: int, node: int,
              host: int, res: resources_lib.Resources) -> dict:
    name = _pod_name(config.cluster_name, index)
    labels = {_LABEL_CLUSTER: config.cluster_name,
              _LABEL_NODE: str(node), _LABEL_HOST: str(host),
              **config.labels}
    container: dict = {
        'name': 'skytpu',
        # Task-pinned container image wins; env default otherwise
        # (resources.image_id — the docker-image story on this
        # substrate).
        'image': res.image_id or os.environ.get('SKYTPU_K8S_IMAGE',
                                                'python:3.11-slim'),
        # The runtime bootstrap (agent start) arrives via command_runner
        # after provisioning, mirroring the VM path; the pod just stays
        # up.
        'command': ['/bin/sh', '-c', 'sleep infinity'],
        'resources': {'requests': {}, 'limits': {}},
    }
    spec: dict = {'restartPolicy': 'Never', 'containers': [container]}
    if res.is_tpu:
        tpu = res.tpu
        gke_acc = GKE_TPU_ACCELERATOR.get(tpu.gen.name)
        if gke_acc is None:
            raise exceptions.InvalidAcceleratorError(
                f'no GKE TPU mapping for generation {tpu.gen.name!r}')
        # Honor an explicitly requested topology; default to the
        # most-square factorization otherwise.
        topology = tpu.topology or \
            'x'.join(str(d) for d in tpu.default_topology())
        spec['nodeSelector'] = {
            'cloud.google.com/gke-tpu-accelerator': gke_acc,
            'cloud.google.com/gke-tpu-topology': topology,
        }
        chips = str(tpu.chips_per_host)
        container['resources']['requests']['google.com/tpu'] = chips
        container['resources']['limits']['google.com/tpu'] = chips
    else:
        if res.cpus:
            container['resources']['requests']['cpu'] = \
                str(res.cpus).rstrip('+')
        if res.memory:
            container['resources']['requests']['memory'] = \
                f'{str(res.memory).rstrip("+")}Gi'
    if res.use_spot:
        spec.setdefault('nodeSelector', {})[
            'cloud.google.com/gke-spot'] = 'true'
        spec['tolerations'] = [{
            'key': 'cloud.google.com/gke-spot',
            'operator': 'Equal', 'value': 'true',
            'effect': 'NoSchedule',
        }]
    if config.volumes:
        # Named PVCs from the volume registry (skypilot_tpu/volumes.py).
        container['volumeMounts'] = [
            {'name': f'vol-{i}', 'mountPath': mount_path}
            for i, mount_path in enumerate(sorted(config.volumes))]
        spec['volumes'] = [
            {'name': f'vol-{i}',
             'persistentVolumeClaim': {
                 'claimName': config.volumes[mount_path]}}
            for i, mount_path in enumerate(sorted(config.volumes))]
    return {
        'apiVersion': 'v1',
        'kind': 'Pod',
        'metadata': {'name': name, 'labels': labels},
        'spec': spec,
    }


def _list_pods(client: _Client, cluster_name: str) -> List[dict]:
    resp = client.request(
        'GET', '/pods',
        params={'labelSelector': f'{_LABEL_CLUSTER}={cluster_name}'})
    if resp.status_code >= 400:
        raise exceptions.ProvisionError(
            f'k8s list pods failed ({resp.status_code}): {resp.text}')
    items = resp.json().get('items', [])
    # Numeric (node, host) order: rank assignment derives from it.
    def key(p):
        labels = p['metadata']['labels']
        return (int(labels.get(_LABEL_NODE, 1 << 30)),
                int(labels.get(_LABEL_HOST, 0)))
    return sorted(items, key=key)


def _group_by_node(pods: List[dict]) -> List[List[dict]]:
    """Host pods -> logical nodes (a multi-host TPU slice is one node)."""
    nodes: Dict[int, List[dict]] = {}
    for pod in pods:
        node = int(pod['metadata']['labels'].get(_LABEL_NODE, 0))
        nodes.setdefault(node, []).append(pod)
    return [nodes[k] for k in sorted(nodes)]


def _node_status(host_pods: List[dict]) -> common.InstanceStatus:
    """A node is as healthy as its sickest host (a TPU slice dies whole:
    one evicted host pod kills the slice's collectives)."""
    statuses = [_pod_status(p) for p in host_pods]
    for bad in (common.InstanceStatus.PREEMPTED,
                common.InstanceStatus.TERMINATED,
                common.InstanceStatus.PENDING):
        if any(s is bad for s in statuses):
            return bad
    return common.InstanceStatus.RUNNING


def _pod_status(pod: dict) -> common.InstanceStatus:
    if pod['metadata'].get('deletionTimestamp'):
        return common.InstanceStatus.TERMINATED
    phase = pod.get('status', {}).get('phase', 'Pending')
    if phase == 'Running':
        return common.InstanceStatus.RUNNING
    if phase == 'Pending':
        return common.InstanceStatus.PENDING
    if phase == 'Failed':
        reason = pod.get('status', {}).get('reason', '')
        # Node-pressure eviction / spot node reclaim present as Failed
        # pods with an eviction reason — the substrate's preemption.
        if reason in ('Evicted', 'Preempted', 'Shutdown'):
            return common.InstanceStatus.PREEMPTED
        return common.InstanceStatus.TERMINATED
    return common.InstanceStatus.TERMINATED


def _unschedulable(pod: dict) -> bool:
    for cond in pod.get('status', {}).get('conditions', []):
        if cond.get('type') == 'PodScheduled' and \
                cond.get('status') == 'False' and \
                cond.get('reason') == 'Unschedulable':
            return True
    return False


# ----- provision API ---------------------------------------------------------
def run_instances(config: common.ProvisionConfig) -> common.ProvisionRecord:
    client = _Client(config.region)
    res = resources_lib.Resources.from_yaml_config(
        dict(config.resources_config))
    existing = {p['metadata']['name']: p
                for p in _list_pods(client, config.cluster_name)}
    live = {common.InstanceStatus.RUNNING, common.InstanceStatus.PENDING}
    # A TPU slice node is one pod per host (GKE multi-host slices).
    pods_per_node = res.hosts_per_node if res.is_tpu else 1
    if config.volumes and config.num_nodes * pods_per_node > 1:
        # A ReadWriteOnce PVC multi-attached across nodes wedges the
        # second pod in ContainerCreating until the wait timeout; fail
        # fast like the GCP disk path does.
        from skypilot_tpu import volumes as volumes_lib
        for vol_name in config.volumes.values():
            vol = volumes_lib.get(vol_name)
            mode = (vol.config.get('access_mode', 'ReadWriteOnce')
                    if vol else 'ReadWriteOnce')
            if mode != 'ReadWriteMany':
                raise exceptions.InvalidRequestError(
                    f'volume {vol_name!r} is {mode}; multi-pod tasks '
                    f'need access_mode ReadWriteMany (or use bucket '
                    f'mounts)')
    instance_ids = []
    resumed = any(_pod_status(p) in live for p in existing.values())
    for node in range(config.num_nodes):
        for host in range(pods_per_node):
            index = node * pods_per_node + host
            name = _pod_name(config.cluster_name, index)
            if host == 0:
                # One instance id per logical node (its head pod), like
                # the TPU path's one-id-per-slice.
                instance_ids.append(name)
            if name in existing:
                if _pod_status(existing[name]) in live:
                    continue
                # Stale Failed/Evicted pod objects block re-creation by
                # name (the GCP path deletes stale nodes the same way
                # before re-provisioning).
                _delete_pod(client, name)
            body = _pod_spec(config, index, node, host, res)
            resp = client.request('POST', '/pods', data=json.dumps(body))
            if resp.status_code == 409:
                continue                      # concurrent create
            if resp.status_code == 403 and 'quota' in resp.text.lower():
                raise exceptions.QuotaExceededError(
                    f'k8s namespace quota: {resp.text}')
            if resp.status_code >= 400:
                raise exceptions.ProvisionError(
                    f'k8s create pod {name} failed '
                    f'({resp.status_code}): {resp.text}')
    return common.ProvisionRecord('kubernetes', config.cluster_name,
                                  config.region, None, instance_ids,
                                  resumed=resumed)


def stop_instances(cluster_name: str, region=None, zone=None) -> None:
    raise exceptions.NotSupportedError(
        'Kubernetes pods cannot be stopped; use down (delete).')


def _delete_pod(client: _Client, name: str) -> None:
    resp = client.request('DELETE', f'/pods/{name}')
    if resp.status_code >= 400 and resp.status_code != 404:
        raise exceptions.ProvisionError(
            f'k8s delete pod {name} failed ({resp.status_code}): '
            f'{resp.text}')


def terminate_instances(cluster_name: str, region=None, zone=None) -> None:
    client = _Client(region)
    for pod in _list_pods(client, cluster_name):
        _delete_pod(client, pod['metadata']['name'])


def wait_instances(cluster_name: str, region=None, zone=None,
                   timeout_s: float = 1800.0) -> None:
    client = _Client(region)
    unschedulable_grace = float(os.environ.get(
        'SKYTPU_K8S_UNSCHEDULABLE_GRACE_S', '30'))
    deadline = time.time() + timeout_s
    started = time.time()
    while True:
        pods = _list_pods(client, cluster_name)
        if not pods:
            raise exceptions.ProvisionError(
                f'no pods found for cluster {cluster_name!r}')
        statuses = [_pod_status(p) for p in pods]
        if all(s is common.InstanceStatus.RUNNING for s in statuses) and \
                all(p.get('status', {}).get('podIP') for p in pods):
            return
        bad = [s for s in statuses
               if s in (common.InstanceStatus.TERMINATED,
                        common.InstanceStatus.PREEMPTED)]
        if bad:
            raise exceptions.ProvisionError(
                f'k8s pods for {cluster_name!r} failed: {statuses}')
        # Stockout detection: kept Unschedulable past the grace window
        # -> clean up and classify for the failover engine.
        if time.time() - started > unschedulable_grace and \
                any(_unschedulable(p) for p in pods):
            terminate_instances(cluster_name, region)
            raise exceptions.InsufficientCapacityError(
                f'k8s cannot schedule pods for {cluster_name!r} '
                f'(Unschedulable: no nodes with the requested '
                f'resources); treat as stockout and fail over')
        if time.time() > deadline:
            raise exceptions.ProvisionError(
                f'k8s pods for {cluster_name!r} not running after '
                f'{timeout_s}s: {statuses}')
        time.sleep(1.0)


def query_instances(cluster_name: str, region=None,
                    zone=None) -> Dict[str, common.InstanceStatus]:
    """Per *logical node* status, keyed by the node's head pod name —
    the same one-id-per-slice shape the TPU provisioner reports."""
    out: Dict[str, common.InstanceStatus] = {}
    client = _Client(region)
    for host_pods in _group_by_node(_list_pods(client, cluster_name)):
        out[host_pods[0]['metadata']['name']] = _node_status(host_pods)
    return out


def get_cluster_info(cluster_name: str, region=None,
                     zone=None) -> common.ClusterInfo:
    instances: List[common.InstanceInfo] = []
    client = _Client(region)
    for host_pods in _group_by_node(_list_pods(client, cluster_name)):
        ips = [p.get('status', {}).get('podIP') for p in host_pods]
        instances.append(common.InstanceInfo(
            instance_id=host_pods[0]['metadata']['name'],
            status=_node_status(host_pods),
            internal_ips=[ip for ip in ips if ip],
            external_ips=[],
            tags=dict(host_pods[0]['metadata'].get('labels', {})),
        ))
    return common.ClusterInfo('kubernetes', cluster_name, instances,
                              ssh_user='root')


def open_ports(cluster_name: str, ports: List[str], region=None,
               zone=None) -> None:
    """Pod IPs are cluster-internal; port exposure is a Service concern
    deliberately left to deployment manifests (the reference's LB story
    on k8s is similar)."""
    del cluster_name, ports, region, zone
