"""GCP catalog — TPU slices are the primary SKU (parity: sky/catalog/gcp_catalog.py).

The reference splits TPUs out of a GPU-shaped CSV (gcp_catalog.py:499-556) and
fakes a `TPU-VM` instance type (:255-277).  Here the TPU table is native:
per-chip-hour prices by generation x zone; the slice price is
`chips * price_chip_hr` and host VMs are included in the slice price (true of
the TPU-VM API — there is no separate host SKU).  VM instance types exist only
for controllers (jobs/serve) and CPU-only tasks.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import pandas as pd

from skypilot_tpu import accelerators as acc_lib
from skypilot_tpu import exceptions
from skypilot_tpu.catalog import common

_tpu_df = common.LazyDataFrame('gcp_tpus.csv')
_vm_df = common.LazyDataFrame('gcp_vms.csv')


@dataclasses.dataclass(frozen=True)
class TpuOffering:
    """One purchasable TPU slice placement."""
    accelerator: str          # canonical, e.g. 'tpu-v5p-128'
    region: str
    zone: str
    hourly_cost: float        # whole slice, on-demand
    hourly_cost_spot: float   # whole slice, spot


def _tpu_rows(generation: str,
              region: Optional[str] = None,
              zone: Optional[str] = None) -> pd.DataFrame:
    df = _tpu_df.read()
    df = df[df['generation'] == generation]
    if region is not None:
        df = df[df['region'] == region]
    if zone is not None:
        df = df[df['zone'] == zone]
    return df


def list_tpu_offerings(accelerator: str,
                       region: Optional[str] = None,
                       zone: Optional[str] = None,
                       use_spot: bool = False) -> List[TpuOffering]:
    """All zones selling this slice, cheapest first."""
    tpu = acc_lib.parse_tpu(accelerator)
    rows = _tpu_rows(tpu.generation, region, zone)
    out = []
    for _, r in rows.iterrows():
        out.append(
            TpuOffering(
                accelerator=tpu.name,
                region=r['region'],
                zone=r['zone'],
                # Whole REQUEST price: chips per slice x slices (multislice
                # xN requests pay for N slices).
                hourly_cost=(float(r['price_chip_hr']) * tpu.num_chips *
                             tpu.num_slices),
                hourly_cost_spot=(float(r['spot_price_chip_hr']) *
                                  tpu.num_chips * tpu.num_slices),
            ))
    out.sort(key=lambda o: o.hourly_cost_spot if use_spot else o.hourly_cost)
    return out


def get_tpu_hourly_cost(accelerator: str,
                        region: Optional[str] = None,
                        zone: Optional[str] = None,
                        use_spot: bool = False) -> float:
    offerings = list_tpu_offerings(accelerator, region, zone, use_spot)
    if not offerings:
        where = zone or region or 'any region'
        raise exceptions.ResourcesUnavailableError(
            f'{accelerator} is not offered in {where}.')
    best = offerings[0]
    return best.hourly_cost_spot if use_spot else best.hourly_cost


def tpu_regions(accelerator: str) -> List[str]:
    tpu = acc_lib.parse_tpu(accelerator)
    return sorted(_tpu_rows(tpu.generation)['region'].unique())


def all_regions() -> List[str]:
    """Every region in the TPU catalog (VM placement is region-flat)."""
    return sorted(_tpu_df.read()['region'].unique())


def tpu_zones(accelerator: str, region: Optional[str] = None) -> List[str]:
    tpu = acc_lib.parse_tpu(accelerator)
    return sorted(_tpu_rows(tpu.generation, region)['zone'].unique())


# ----- VM instance types (controllers / CPU tasks) ---------------------------
def get_vm_spec(instance_type: str) -> Tuple[float, float]:
    """(vcpus, memory_gb) of an instance type."""
    df = _vm_df.read()
    rows = df[df['instance_type'] == instance_type]
    if rows.empty:
        raise exceptions.InvalidResourcesError(
            f'Unknown GCP instance type: {instance_type!r}')
    r = rows.iloc[0]
    return float(r['vcpus']), float(r['memory_gb'])


def get_vm_hourly_cost(instance_type: str, use_spot: bool = False) -> float:
    df = _vm_df.read()
    rows = df[df['instance_type'] == instance_type]
    if rows.empty:
        raise exceptions.InvalidResourcesError(
            f'Unknown GCP instance type: {instance_type!r}')
    r = rows.iloc[0]
    return float(r['spot_price_hr'] if use_spot else r['price_hr'])


def get_default_instance_type(cpus: Optional[str] = None,
                              memory: Optional[str] = None) -> Optional[str]:
    """Cheapest instance type satisfying the cpu/mem spec
    (reference: per-cloud get_default_instance_type)."""
    df = _vm_df.read()
    if cpus is None and memory is None:
        cpus = '4+'   # controller-friendly default
    df = common.parse_cpus_filter(df, cpus)
    df = common.parse_memory_filter(df, memory)
    if df.empty:
        return None
    return df.sort_values('price_hr').iloc[0]['instance_type']


def validate_region_zone(
        region: Optional[str],
        zone: Optional[str],
        for_tpu: bool = True) -> None:
    """Validate a placement pin.  TPU placements must exist in the TPU
    catalog; VM-only placements (region-flat pricing) only get the
    zone-in-region consistency check."""
    if zone is not None and region is not None and \
            not zone.startswith(region):
        raise exceptions.InvalidInfraError(
            f'Zone {zone!r} is not in region {region!r}')
    if not for_tpu:
        return
    df = _tpu_df.read()
    if region is not None and region not in set(df['region']):
        raise exceptions.InvalidInfraError(f'Unknown GCP region {region!r}')
    if zone is not None and zone not in set(df['zone']):
        raise exceptions.InvalidInfraError(f'Unknown GCP zone {zone!r}')


def list_accelerators(
        name_filter: Optional[str] = None) -> Dict[str, List[TpuOffering]]:
    """Catalog dump for `accelerators list`: canonical name → offerings."""
    out: Dict[str, List[TpuOffering]] = {}
    for name in acc_lib.list_tpu_types():
        if name_filter and name_filter.lower() not in name.lower():
            continue
        offerings = list_tpu_offerings(name)
        if offerings:
            out[name] = offerings
    return out


def invalidate_cache() -> None:
    _tpu_df.invalidate()
    _vm_df.invalidate()
