"""Catalog facade — cloud-dispatched pricing/feasibility queries.

Parity target: `sky/catalog/__init__.py` (per-cloud `*_catalog.py` modules
behind one facade).  Clouds here: `gcp` (TPU-first) and `local` (free,
always-feasible, used by dev/tests the way the reference uses mocked clouds).
"""
from __future__ import annotations

from typing import Dict, List, Optional, TYPE_CHECKING

from skypilot_tpu import exceptions
from skypilot_tpu.catalog import gcp_catalog
from skypilot_tpu.catalog.gcp_catalog import TpuOffering

if TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib

__all__ = [
    'TpuOffering', 'get_hourly_cost', 'list_accelerators', 'list_offerings',
    'get_regions', 'get_zones', 'get_default_instance_type', 'gcp_catalog',
]


def get_hourly_cost(resources: 'resources_lib.Resources') -> float:
    """$/hr for one node of `resources` (cheapest placement if region/zone
    unset).  TPU slice prices include the host VMs."""
    cloud = resources.cloud
    if cloud in ('local', 'slurm'):
        return 0.0          # slurm allocations are quota'd, not billed
    if cloud == 'aws':
        from skypilot_tpu import clouds as clouds_lib
        return clouds_lib.get_cloud('aws').hourly_cost(resources)
    if resources.is_tpu:
        tpu = resources.tpu
        assert tpu is not None
        return gcp_catalog.get_tpu_hourly_cost(tpu.name,
                                               region=resources.region,
                                               zone=resources.zone,
                                               use_spot=resources.use_spot)
    if resources.instance_type is not None:
        return gcp_catalog.get_vm_hourly_cost(resources.instance_type,
                                              use_spot=resources.use_spot)
    if resources.accelerators:
        raise exceptions.ResourcesUnavailableError(
            f'No GPU offerings in the GCP catalog for '
            f'{resources.accelerator_name}; this build is TPU-first. '
            f'Use accelerators: tpu-<gen>-<size>.')
    # CPU-only with no instance type: price the default pick.
    instance_type = gcp_catalog.get_default_instance_type(
        resources.cpus, resources.memory)
    if instance_type is None:
        raise exceptions.ResourcesUnavailableError(
            f'No instance type satisfies cpus={resources.cpus} '
            f'memory={resources.memory}.')
    return gcp_catalog.get_vm_hourly_cost(instance_type,
                                          use_spot=resources.use_spot)


def list_offerings(
        resources: 'resources_lib.Resources') -> List[TpuOffering]:
    """Concrete (region, zone, price) placements for a TPU request,
    cheapest first, honoring any region/zone pin."""
    if not resources.is_tpu:
        raise exceptions.InvalidResourcesError(
            'list_offerings is TPU-only; VM placement is region-flat.')
    tpu = resources.tpu
    assert tpu is not None
    return gcp_catalog.list_tpu_offerings(tpu.name,
                                          region=resources.region,
                                          zone=resources.zone,
                                          use_spot=resources.use_spot)


def get_regions(resources: 'resources_lib.Resources') -> List[str]:
    if resources.cloud == 'local':
        return ['local']
    if resources.cloud == 'slurm':
        return [resources.region or 'default']   # region = partition
    if resources.is_tpu:
        assert resources.tpu is not None
        regions = gcp_catalog.tpu_regions(resources.tpu.name)
    else:
        regions = gcp_catalog.all_regions()
    if resources.region is not None:
        regions = [r for r in regions if r == resources.region]
    return regions


def get_zones(resources: 'resources_lib.Resources',
              region: Optional[str] = None) -> List[str]:
    if resources.cloud == 'local':
        return ['local']
    if resources.cloud == 'slurm':
        return []                                # partitions have no zones
    if resources.is_tpu:
        assert resources.tpu is not None
        zones = gcp_catalog.tpu_zones(resources.tpu.name,
                                      region or resources.region)
    else:
        zones = []
    if resources.zone is not None:
        zones = [z for z in zones if z == resources.zone]
    return zones


def list_accelerators(
        name_filter: Optional[str] = None) -> Dict[str, List[TpuOffering]]:
    return gcp_catalog.list_accelerators(name_filter)


def get_default_instance_type(cpus: Optional[str] = None,
                              memory: Optional[str] = None) -> Optional[str]:
    return gcp_catalog.get_default_instance_type(cpus, memory)
