"""AWS EC2 instance catalog (parity: sky/catalog/aws_catalog.py).

CPU families only: this build is TPU-first — AWS is the second compute
substrate for controllers, CPU tasks and S3-adjacent work, not an
accelerator cloud.  Same CSV-with-staleness-stamp mechanics as the GCP
catalog (catalog/common.py); prices are per-region (EC2 list prices
differ across regions, unlike the region-flat GCE sheet we ship).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu.catalog import common

_vm_df = common.LazyDataFrame('aws_vms.csv')

DEFAULT_REGION = 'us-east-1'


def regions() -> List[str]:
    return sorted(_vm_df.read()['region'].unique())


def _rows(instance_type: str, region: Optional[str] = None):
    df = _vm_df.read()
    df = df[df['instance_type'] == instance_type]
    if region is not None:
        df = df[df['region'] == region]
    return df


def get_vm_spec(instance_type: str) -> Tuple[float, float]:
    """(vcpus, memory_gb)."""
    rows = _rows(instance_type)
    if rows.empty:
        raise exceptions.InvalidResourcesError(
            f'Unknown EC2 instance type: {instance_type!r}')
    r = rows.iloc[0]
    return float(r['vcpus']), float(r['memory_gb'])


def get_vm_hourly_cost(instance_type: str,
                       region: Optional[str] = None,
                       use_spot: bool = False) -> float:
    rows = _rows(instance_type, region)
    if rows.empty:
        where = region or 'any region'
        raise exceptions.ResourcesUnavailableError(
            f'{instance_type} is not offered in {where} '
            f'(AWS catalog).')
    col = 'spot_price_hr' if use_spot else 'price_hr'
    return float(rows.sort_values(col).iloc[0][col])


def get_default_instance_type(cpus: Optional[str] = None,
                              memory: Optional[str] = None,
                              region: Optional[str] = None
                              ) -> Optional[str]:
    """Cheapest type satisfying the cpu/memory bounds ('4', '4+')."""
    df = _vm_df.read()
    if region is not None:
        df = df[df['region'] == region]
    if cpus is None and memory is None:
        cpus = '4+'     # controller-friendly default, matches GCP path
    df = common.parse_cpus_filter(df, cpus)
    df = common.parse_memory_filter(df, memory)
    if df.empty:
        return None
    return df.sort_values('price_hr').iloc[0]['instance_type']
