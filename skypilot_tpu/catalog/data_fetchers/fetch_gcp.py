"""Regenerate the GCP TPU/VM catalogs from the Cloud Billing API.

Analog of the reference's `sky/catalog/data_fetchers/fetch_gcp.py` (which
builds price tables from the billing SKU list).  Writes refreshed CSVs to
`~/.skytpu/catalogs/<schema>/`, which `catalog.common.resolve_catalog_path`
prefers over the bundled copies.

The SKU source is injectable: the real Cloud Billing API (network + GCP
credentials + google-api-python-client), or — with
``SKYTPU_BILLING_FIXTURE=<path.json>`` — a recorded page list committed to
the repo (tests/fixtures/gcp_billing_skus.json), so the whole
SKU-parsing → price-derivation → CSV-writing path runs hermetically in CI
(vcr-style; the fixture file mirrors the API's response shape exactly).

VM prices are derived the way the reference does: an instance type's
$/hr = vcpus x core-SKU price + memory_gb x ram-SKU price for its
family; the vcpu/memory shapes come from the bundled table (the machine-
types API is the authority on shapes, billing only prices them).

Usage: python -m skypilot_tpu.catalog.data_fetchers.fetch_gcp
       (or `skytpu catalog refresh`)
"""
from __future__ import annotations

import json
import os
import re
import sys
from typing import Dict, Iterable, List, Optional, Tuple

from skypilot_tpu.catalog import common

_BILLING_SERVICE_GCE = 'services/6F81-5844-456A'  # Compute Engine SKUs
_TPU_SKU_RE = re.compile(r'Tpu[- ]?(v\d+[a-z]*)', re.IGNORECASE)

# VM families whose core/ram SKUs we price.  The SKU descriptions carry
# the family name ('N2 Instance Core running in Americas', 'E2 Instance
# Ram ...'); spot SKUs say 'Spot Preemptible'.
_VM_FAMILIES = ('e2', 'n2', 'c3', 'a2', 'g2', 'm3', 'c3d')
_VM_SKU_RE = re.compile(
    r'^(?:Spot Preemptible )?(' + '|'.join(f.upper() for f in _VM_FAMILIES)
    + r')(?: Instance)? (Core|Ram) running', re.IGNORECASE)


def _unwrap_fixture(obj):
    """Fixture files may wrap the raw page list with recording
    provenance: {"recorded_at": "YYYY-MM-DD", "pages": [...]}.  A bare
    list/dict of pages (vcr-style) stays supported."""
    if isinstance(obj, dict) and 'pages' in obj:
        obj = obj['pages']
    return [obj] if isinstance(obj, dict) else list(obj)


def fixture_recorded_at() -> Optional[float]:
    """Epoch seconds the active billing fixture was recorded, from its
    `recorded_at` field ("YYYY-MM-DD" or epoch seconds).  None when no
    fixture is active or it carries no provenance.  Threaded into the
    written catalogs' .meta.json so staleness tracks the DATA's age,
    not the time someone last replayed the recording."""
    fixture = os.environ.get('SKYTPU_BILLING_FIXTURE')
    if not fixture:
        return None
    try:
        with open(fixture, encoding='utf-8') as f:
            obj = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(obj, dict):
        return None
    raw = obj.get('recorded_at')
    if raw is None:
        return None
    try:
        return float(raw)
    except (TypeError, ValueError):
        pass
    try:
        import datetime
        return datetime.datetime.strptime(
            str(raw), '%Y-%m-%d').replace(
                tzinfo=datetime.timezone.utc).timestamp()
    except ValueError:
        return None


def iter_sku_pages() -> Iterable[dict]:
    """Yield billing-API SKU response pages, from the recorded fixture
    (SKYTPU_BILLING_FIXTURE) or the live API."""
    fixture = os.environ.get('SKYTPU_BILLING_FIXTURE')
    if fixture:
        with open(fixture, encoding='utf-8') as f:
            pages = json.load(f)
        yield from _unwrap_fixture(pages)
        return
    try:
        import googleapiclient.discovery  # type: ignore
    except ImportError as e:
        raise SystemExit(
            'google-api-python-client is required to refresh catalogs; '
            'the bundled catalog remains in use.') from e
    billing = googleapiclient.discovery.build('cloudbilling', 'v1')
    req = billing.services().skus().list(parent=_BILLING_SERVICE_GCE)
    while req is not None:
        resp = req.execute()
        yield resp
        req = billing.services().skus().list_next(req, resp)


def _sku_price(sku: dict) -> Optional[float]:
    pricing = sku.get('pricingInfo', [])
    if not pricing:
        return None
    rate = pricing[0]['pricingExpression']['tieredRates'][-1]['unitPrice']
    return float(rate.get('units', 0)) + rate.get('nanos', 0) / 1e9


def fetch_tpu_prices(pages: Optional[Iterable[dict]] = None
                     ) -> List[Dict[str, object]]:
    rows: List[Dict[str, object]] = []
    pages = _unwrap_fixture(pages) if pages is not None else None
    for resp in (pages if pages is not None else iter_sku_pages()):
        for sku in resp.get('skus', []):
            m = _TPU_SKU_RE.search(sku.get('description', ''))
            if not m:
                continue
            gen = m.group(1).lower()
            spot = 'preemptible' in sku.get('description', '').lower()
            price = _sku_price(sku)
            if price is None:
                continue
            for region in sku.get('serviceRegions', []):
                rows.append({
                    'generation': gen,
                    'region': region,
                    'spot': spot,
                    'price_chip_hr': price,
                })
    return rows


def fetch_vm_unit_prices(pages: Optional[Iterable[dict]] = None
                         ) -> Dict[Tuple[str, str, str, bool], float]:
    """{(family, 'core'|'ram', region, spot): unit $/hr}."""
    out: Dict[Tuple[str, str, str, bool], float] = {}
    pages = _unwrap_fixture(pages) if pages is not None else None
    for resp in (pages if pages is not None else iter_sku_pages()):
        for sku in resp.get('skus', []):
            desc = sku.get('description', '')
            m = _VM_SKU_RE.match(desc)
            if not m:
                continue
            family = m.group(1).lower()
            unit = m.group(2).lower()     # core | ram
            spot = desc.lower().startswith('spot preemptible')
            price = _sku_price(sku)
            if price is None:
                continue
            for region in sku.get('serviceRegions', []):
                out[(family, unit, region, spot)] = price
    return out


def derive_vm_rows(unit_prices: Dict[Tuple[str, str, str, bool], float],
                   shapes: 'List[Tuple[str, float, float]]',
                   region: str = 'us-central1'
                   ) -> List[Dict[str, object]]:
    """Price each (instance_type, vcpus, memory_gb) shape from its
    family's core/ram unit SKUs: $/hr = vcpus*core + mem*ram."""
    rows = []
    for instance_type, vcpus, mem in shapes:
        family = instance_type.split('-', 1)[0].split('.')[0]
        core = unit_prices.get((family, 'core', region, False))
        ram = unit_prices.get((family, 'ram', region, False))
        if core is None or ram is None:
            continue
        spot_core = unit_prices.get((family, 'core', region, True),
                                    core * 0.3)
        spot_ram = unit_prices.get((family, 'ram', region, True),
                                   ram * 0.3)
        rows.append({
            'instance_type': instance_type,
            'vcpus': vcpus,
            'memory_gb': mem,
            'price_hr': round(vcpus * core + mem * ram, 4),
            'spot_price_hr': round(vcpus * spot_core + mem * spot_ram, 4),
        })
    return rows


def main() -> int:
    out_dir = common.catalog_override_dir()
    os.makedirs(out_dir, exist_ok=True)
    pages = list(iter_sku_pages())
    rows = fetch_tpu_prices(pages)
    if not rows:
        print('No TPU SKUs returned; keeping bundled catalog.',
              file=sys.stderr)
        return 1
    # Merge on-demand + spot rows into the bundled-catalog schema.  SKU
    # descriptions use marketing names ('v5e'); canonicalize through the
    # accelerator registry so gcp_catalog's generation filter matches.
    from skypilot_tpu import accelerators as acc_lib
    import pandas as pd
    alias_to_gen = acc_lib.alias_to_generation()
    bundled = pd.read_csv(
        os.path.join(common._BUNDLED_DIR, 'gcp_tpus.csv'))
    known_zones: Dict[tuple, List[str]] = {}
    for _, r in bundled.iterrows():
        known_zones.setdefault((r['generation'], r['region']),
                               []).append(r['zone'])
    merged: Dict[tuple, Dict[str, float]] = {}
    for r in rows:
        gen = alias_to_gen.get(str(r['generation']).lower())
        if gen is None:
            continue
        key = (gen, r['region'])
        slot = 'spot_price_chip_hr' if r['spot'] else 'price_chip_hr'
        merged.setdefault(key, {})[slot] = float(r['price_chip_hr'])
    path = os.path.join(out_dir, 'gcp_tpus.csv')
    with open(path, 'w', encoding='utf-8') as f:
        f.write('generation,region,zone,price_chip_hr,spot_price_chip_hr\n')
        for (gen, region), prices in sorted(merged.items()):
            od = prices.get('price_chip_hr')
            sp = prices.get('spot_price_chip_hr', (od or 0) * 0.5)
            if od is None:
                continue
            # Billing SKUs are per-region; zones come from the bundled
            # table (the TPU locations API is the authority — regions
            # without known zones are skipped rather than invented).
            for zone in known_zones.get((gen, region), []):
                f.write(f'{gen},{region},{zone},{od},{sp}\n')
    # Staleness provenance: a fixture replay stamps the RECORDING
    # date, so the catalog's age reflects the data, not the replay.
    recorded_at = fixture_recorded_at()
    common.write_catalog_metadata(path, generated_at=recorded_at)
    print(f'Wrote {path}')

    # VM catalog: price the bundled shapes from core/ram unit SKUs.
    # Families without unit SKUs keep their BUNDLED prices — the refresh
    # must never make a previously-priced instance type unknown (the
    # override CSV shadows the bundled one entirely).
    unit_prices = fetch_vm_unit_prices(pages)
    bundled_vms = pd.read_csv(
        os.path.join(common._BUNDLED_DIR, 'gcp_vms.csv'))
    shapes = [(r['instance_type'], float(r['vcpus']),
               float(r['memory_gb'])) for _, r in bundled_vms.iterrows()]
    derived = {r['instance_type']: r
               for r in derive_vm_rows(unit_prices, shapes)}
    if derived:
        vm_path = os.path.join(out_dir, 'gcp_vms.csv')
        with open(vm_path, 'w', encoding='utf-8') as f:
            f.write('instance_type,vcpus,memory_gb,price_hr,'
                    'spot_price_hr\n')
            for _, b in bundled_vms.iterrows():
                r = derived.get(b['instance_type'])
                if r is not None:
                    f.write(f"{r['instance_type']},{r['vcpus']},"
                            f"{r['memory_gb']},{r['price_hr']},"
                            f"{r['spot_price_hr']}\n")
                else:
                    f.write(f"{b['instance_type']},{b['vcpus']},"
                            f"{b['memory_gb']},{b['price_hr']},"
                            f"{b['spot_price_hr']}\n")
        common.write_catalog_metadata(vm_path, generated_at=recorded_at)
        print(f'Wrote {vm_path}')
    return 0


if __name__ == '__main__':
    sys.exit(main())
