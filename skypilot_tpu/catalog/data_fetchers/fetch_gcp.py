"""Regenerate the GCP TPU/VM catalogs from the Cloud Billing API.

Analog of the reference's `sky/catalog/data_fetchers/fetch_gcp.py` (which
builds TPU price tables from the billing SKU list).  Writes refreshed CSVs to
`~/.skytpu/catalogs/<schema>/`, which `catalog.common.resolve_catalog_path`
prefers over the bundled copies.  Requires network + GCP credentials, so it is
an offline tool, never called on the hot path.

Usage: python -m skypilot_tpu.catalog.data_fetchers.fetch_gcp
"""
from __future__ import annotations

import os
import re
import sys
from typing import Dict, List  # noqa: F401  (List used in main)

from skypilot_tpu.catalog import common

_BILLING_SERVICE_GCE = 'services/6F81-5844-456A'  # Compute Engine SKUs
_TPU_SKU_RE = re.compile(r'Tpu[- ]?(v\d+[a-z]*)', re.IGNORECASE)


def fetch_tpu_prices() -> List[Dict[str, object]]:
    try:
        import googleapiclient.discovery  # type: ignore
    except ImportError as e:
        raise SystemExit(
            'google-api-python-client is required to refresh catalogs; '
            'the bundled catalog remains in use.') from e
    billing = googleapiclient.discovery.build('cloudbilling', 'v1')
    rows: List[Dict[str, object]] = []
    req = billing.services().skus().list(parent=_BILLING_SERVICE_GCE)
    while req is not None:
        resp = req.execute()
        for sku in resp.get('skus', []):
            m = _TPU_SKU_RE.search(sku.get('description', ''))
            if not m:
                continue
            gen = m.group(1).lower()
            spot = 'preemptible' in sku.get('description', '').lower()
            for region in sku.get('serviceRegions', []):
                pricing = sku.get('pricingInfo', [])
                if not pricing:
                    continue
                expr = pricing[0]['pricingExpression']
                rate = expr['tieredRates'][-1]['unitPrice']
                price = (float(rate.get('units', 0)) +
                         rate.get('nanos', 0) / 1e9)
                rows.append({
                    'generation': gen,
                    'region': region,
                    'spot': spot,
                    'price_chip_hr': price,
                })
        req = billing.services().skus().list_next(req, resp)
    return rows


def main() -> int:
    out_dir = common.catalog_override_dir()
    os.makedirs(out_dir, exist_ok=True)
    rows = fetch_tpu_prices()
    if not rows:
        print('No TPU SKUs returned; keeping bundled catalog.',
              file=sys.stderr)
        return 1
    # Merge on-demand + spot rows into the bundled-catalog schema.  SKU
    # descriptions use marketing names ('v5e'); canonicalize through the
    # accelerator registry so gcp_catalog's generation filter matches.
    from skypilot_tpu import accelerators as acc_lib
    import pandas as pd
    alias_to_gen = acc_lib.alias_to_generation()
    bundled = pd.read_csv(
        os.path.join(common._BUNDLED_DIR, 'gcp_tpus.csv'))
    known_zones: Dict[tuple, List[str]] = {}
    for _, r in bundled.iterrows():
        known_zones.setdefault((r['generation'], r['region']),
                               []).append(r['zone'])
    merged: Dict[tuple, Dict[str, float]] = {}
    for r in rows:
        gen = alias_to_gen.get(str(r['generation']).lower())
        if gen is None:
            continue
        key = (gen, r['region'])
        slot = 'spot_price_chip_hr' if r['spot'] else 'price_chip_hr'
        merged.setdefault(key, {})[slot] = float(r['price_chip_hr'])
    path = os.path.join(out_dir, 'gcp_tpus.csv')
    with open(path, 'w', encoding='utf-8') as f:
        f.write('generation,region,zone,price_chip_hr,spot_price_chip_hr\n')
        for (gen, region), prices in sorted(merged.items()):
            od = prices.get('price_chip_hr')
            sp = prices.get('spot_price_chip_hr', (od or 0) * 0.5)
            if od is None:
                continue
            # Billing SKUs are per-region; zones come from the bundled
            # table (the TPU locations API is the authority — regions
            # without known zones are skipped rather than invented).
            for zone in known_zones.get((gen, region), []):
                f.write(f'{gen},{region},{zone},{od},{sp}\n')
    common.write_catalog_metadata(path)   # staleness provenance
    print(f'Wrote {path}')
    return 0


if __name__ == '__main__':
    sys.exit(main())
