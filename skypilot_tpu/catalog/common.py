"""Catalog loading infrastructure (capability parity: sky/catalog/common.py).

The reference lazily downloads hosted CSVs with staleness-based refresh
(sky/catalog/common.py:165 `read_catalog`, URL at :211) into `LazyDataFrame`s
(:124).  Here catalogs ship *bundled* with the package (TPU SKUs have no good
public pricing API — examples/tpu/v6e/README.md:7 in the reference notes v6e
prices missing entirely), and a user-local override directory
(`~/.skytpu/catalogs/<schema>/`) takes precedence so `data_fetchers` can
refresh them out-of-band without a package upgrade.
"""
from __future__ import annotations

import os
import threading
from typing import Callable, Optional

import pandas as pd

CATALOG_SCHEMA_VERSION = 'v1'
_BUNDLED_DIR = os.path.join(os.path.dirname(__file__), 'data')


def catalog_override_dir() -> str:
    return os.path.expanduser(
        os.environ.get(
            'SKYTPU_CATALOG_DIR',
            os.path.join('~/.skytpu/catalogs', CATALOG_SCHEMA_VERSION)))


def resolve_catalog_path(filename: str) -> str:
    """User-refreshed catalog wins over the bundled one."""
    override = os.path.join(catalog_override_dir(), filename)
    if os.path.exists(override):
        return override
    return os.path.join(_BUNDLED_DIR, filename)


class LazyDataFrame:
    """Thread-safe lazy CSV load (analog of reference LazyDataFrame,
    sky/catalog/common.py:124).  Re-resolves the path on each cold load so a
    refreshed user catalog is picked up after `invalidate()`."""

    def __init__(self, filename: str,
                 postprocess: Optional[Callable[[pd.DataFrame],
                                                pd.DataFrame]] = None):
        self._filename = filename
        self._postprocess = postprocess
        self._df: Optional[pd.DataFrame] = None
        self._lock = threading.Lock()

    def read(self) -> pd.DataFrame:
        df = self._df
        if df is None:
            with self._lock:
                df = self._df
                if df is None:
                    df = pd.read_csv(resolve_catalog_path(self._filename))
                    if self._postprocess is not None:
                        df = self._postprocess(df)
                    self._df = df
        return df

    def invalidate(self) -> None:
        with self._lock:
            self._df = None


def parse_cpus_filter(df: pd.DataFrame, cpus: Optional[str],
                      col: str = 'vcpus') -> pd.DataFrame:
    """Filter rows by a '4' (exact) or '4+' (at least) spec
    (reference: sky/catalog/common.py:419 `_filter_with_cpus`)."""
    if cpus is None:
        return df
    spec = str(cpus).strip()
    if spec.endswith('+'):
        return df[df[col] >= float(spec[:-1])]
    return df[df[col] == float(spec)]


def parse_memory_filter(df: pd.DataFrame, memory: Optional[str],
                        col: str = 'memory_gb') -> pd.DataFrame:
    return parse_cpus_filter(df, memory, col)
