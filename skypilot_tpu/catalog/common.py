"""Catalog loading infrastructure (capability parity: sky/catalog/common.py).

The reference lazily downloads hosted CSVs with staleness-based refresh
(sky/catalog/common.py:165 `read_catalog`, URL at :211) into `LazyDataFrame`s
(:124).  Here catalogs ship *bundled* with the package (TPU SKUs have no good
public pricing API — examples/tpu/v6e/README.md:7 in the reference notes v6e
prices missing entirely), and a user-local override directory
(`~/.skytpu/catalogs/<schema>/`) takes precedence so `data_fetchers` can
refresh them out-of-band without a package upgrade.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, Optional

import pandas as pd

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

CATALOG_SCHEMA_VERSION = 'v1'
_BUNDLED_DIR = os.path.join(os.path.dirname(__file__), 'data')

# Pricing data decays: after this many days a catalog is flagged stale
# (warning on load + surfaced in `skytpu check`), prompting a
# data_fetchers refresh.  The reference refreshes hosted CSVs on the
# same staleness trigger (sky/catalog/common.py:165).
try:
    STALENESS_DAYS = float(
        os.environ.get('SKYTPU_CATALOG_STALENESS_DAYS', '45'))
except ValueError:
    STALENESS_DAYS = 45.0   # malformed env must not break imports


# Per-catalog refresh remediation (only files a fetcher actually
# regenerates may point at that fetcher).
_REFRESH_HINTS = {
    'gcp_tpus.csv': '`skytpu catalog refresh` (or python -m '
                    'skypilot_tpu.catalog.data_fetchers.fetch_gcp)',
    'gcp_vms.csv': '`skytpu catalog refresh`',
}


def catalog_override_dir() -> str:
    return os.path.expanduser(
        os.environ.get(
            'SKYTPU_CATALOG_DIR',
            os.path.join('~/.skytpu/catalogs', CATALOG_SCHEMA_VERSION)))


def resolve_catalog_path(filename: str) -> str:
    """User-refreshed catalog wins over the bundled one."""
    override = os.path.join(catalog_override_dir(), filename)
    if os.path.exists(override):
        return override
    return os.path.join(_BUNDLED_DIR, filename)


def catalog_generated_at(filename: str) -> Optional[float]:
    """Epoch seconds the catalog was generated, from the sidecar
    `<filename>.meta.json` the fetchers write (bundled catalogs carry
    one checked in at curation time).  None = unknown provenance."""
    meta_path = resolve_catalog_path(filename) + '.meta.json'
    if not os.path.exists(meta_path):
        return None
    try:
        with open(meta_path, encoding='utf-8') as f:
            return float(json.load(f)['generated_at'])
    except (OSError, ValueError, KeyError, TypeError,
            json.JSONDecodeError):
        return None   # corrupt sidecar = unknown provenance, not a crash


def write_catalog_metadata(path: str,
                           generated_at: Optional[float] = None) -> None:
    """Sidecar writer for data_fetchers: stamps `generated_at` — now by
    default, or the DATA's recording time when the fetch came from a
    recorded fixture (replaying an old recording must not make stale
    prices look fresh)."""
    with open(path + '.meta.json', 'w', encoding='utf-8') as f:
        json.dump({'generated_at': (time.time() if generated_at is None
                                    else float(generated_at))}, f)


def catalog_staleness(filename: str) -> Dict[str, object]:
    """{'age_days': float|None, 'stale': bool} for `skytpu check`."""
    generated = catalog_generated_at(filename)
    if generated is None:
        return {'age_days': None, 'stale': True}
    age_days = max(0.0, (time.time() - generated) / 86400.0)
    return {'age_days': round(age_days, 1),
            'stale': age_days > STALENESS_DAYS}


class LazyDataFrame:
    """Thread-safe lazy CSV load (analog of reference LazyDataFrame,
    sky/catalog/common.py:124).  Re-resolves the path on each cold load so a
    refreshed user catalog is picked up after `invalidate()`."""

    def __init__(self, filename: str,
                 postprocess: Optional[Callable[[pd.DataFrame],
                                                pd.DataFrame]] = None):
        self._filename = filename
        self._postprocess = postprocess
        self._df: Optional[pd.DataFrame] = None
        self._lock = threading.Lock()

    def read(self) -> pd.DataFrame:
        df = self._df
        if df is None:
            with self._lock:
                df = self._df
                if df is None:
                    df = pd.read_csv(resolve_catalog_path(self._filename))
                    if self._postprocess is not None:
                        df = self._postprocess(df)
                    staleness = catalog_staleness(self._filename)
                    if staleness['stale']:
                        age = staleness['age_days']
                        hint = _REFRESH_HINTS.get(
                            self._filename,
                            f'place a refreshed CSV (+ .meta.json '
                            f'sidecar) in {catalog_override_dir()}')
                        logger.warning(
                            f'catalog {self._filename} is '
                            f'{"of unknown age" if age is None else f"{age} days old"}'
                            f' (staleness threshold {STALENESS_DAYS:.0f}d); '
                            f'prices may be wrong — refresh: {hint}')
                    self._df = df
        return df

    def invalidate(self) -> None:
        with self._lock:
            self._df = None


def parse_cpus_filter(df: pd.DataFrame, cpus: Optional[str],
                      col: str = 'vcpus') -> pd.DataFrame:
    """Filter rows by a '4' (exact) or '4+' (at least) spec
    (reference: sky/catalog/common.py:419 `_filter_with_cpus`)."""
    if cpus is None:
        return df
    spec = str(cpus).strip()
    if spec.endswith('+'):
        return df[df[col] >= float(spec[:-1])]
    return df[df[col] == float(spec)]


def parse_memory_filter(df: pd.DataFrame, memory: Optional[str],
                        col: str = 'memory_gb') -> pd.DataFrame:
    return parse_cpus_filter(df, memory, col)
